"""Online draft distillation — the serving fleet teaches its own
speculative draft from live traffic (ROADMAP item 1b).

The closed loop, each leg an existing subsystem pointed at the next:

    serve (exactly-once txn window)
      └─ distill topic (wire.py — committed completions, CRC-framed)
           └─ DistillTrainer (trainer.py — KafkaStream + make_train_step
              over the layer-truncated draft)
                └─ checkpoint topic (source/checkpoint_wire.py —
                   versioned, torn-publish-rejecting)
                     └─ DistillController (controller.py — windowed
                        live-α gate, hysteresis, typed trace decisions)
                          └─ swap_draft_params (serve_spec.py — between
                             ticks, no quiesce) ─ back to serve

Committed tokens are invariant around the whole cycle: the corpus only
ever holds committed tokens (publisher rides the commit window), and a
draft refresh only changes the PROPOSER (the target's verification
commits) — both ends differential-tested and SIGKILL-matrixed.
"""

from torchkafka_tpu.distill.controller import (
    DistillController,
    DistillPolicy,
    InProcessDistillDriver,
)
from torchkafka_tpu.distill.trainer import DistillTrainer
from torchkafka_tpu.distill.wire import (
    decode_completion,
    distill_processor,
    encode_completion,
)
from torchkafka_tpu.distill.worker import run_distill_worker

__all__ = [
    "DistillController",
    "DistillPolicy",
    "DistillTrainer",
    "InProcessDistillDriver",
    "decode_completion",
    "distill_processor",
    "encode_completion",
    "run_distill_worker",
]

"""Framed wire format for the distill topic — committed completions only.

One frame = one COMMITTED completion: the prompt ids the serving fleet
admitted, the tokens its target model actually committed (exactly-once
replicas stage the frame inside the same transaction as the output and
the offset, so no divergent-canary or zombie output ever reaches the
corpus), the tenant key, and the model version that produced it. Same
no-pickle discipline as ``source/checkpoint_wire.py``: a magic, a
length-prefixed JSON header, raw little-endian int32 payload bytes, and
a CRC over the payload so a torn frame is REJECTED, never trained on.

Layout::

    b"DSTL" | u32 header_len (BE) | JSON header | prompt int32 | tokens int32

Header fields: ``v`` (wire version), ``mv`` (model version that served
it), ``tenant`` (record key, latin-1 round-trip — arbitrary bytes
survive), ``np``/``nt`` (prompt/token counts), ``crc`` (crc32 of the
concatenated payload bytes).

``distill_processor`` adapts frames to the EXISTING training plane: a
per-record KafkaStream processor returning ``{"tokens": [S] int32,
"mask": [S] int32}`` — prompt ++ committed tokens left-aligned into a
fixed training width (static shapes; the stream's batcher stacks them),
mask 1 over real positions. Malformed frames return ``None`` (the
stream's documented DROP signal): the corpus is at-least-once, so a
torn record costs one sample, not the trainer.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from torchkafka_tpu.errors import DistillWireError

MAGIC = b"DSTL"
WIRE_VERSION = 1
_LEN = struct.Struct(">I")
# JSON headers are small; anything past this is a corrupt length field,
# not a real header — bound it so a torn frame can't ask for gigabytes.
_MAX_HEADER = 1 << 16


def encode_completion(
    prompt, tokens, *, tenant: bytes | None, model_version: int
) -> bytes:
    """Frame one committed completion. ``tenant`` is the raw record key
    (``None`` → empty); prompt/tokens are int32 id sequences."""
    p = np.ascontiguousarray(np.asarray(prompt, np.int32))
    t = np.ascontiguousarray(np.asarray(tokens, np.int32))
    if p.ndim != 1 or t.ndim != 1:
        raise DistillWireError("prompt/tokens must be 1-D id sequences")
    payload = p.tobytes() + t.tobytes()
    header = json.dumps(
        {
            "v": WIRE_VERSION,
            "mv": int(model_version),
            "tenant": (tenant or b"").decode("latin-1"),
            "np": int(p.shape[0]),
            "nt": int(t.shape[0]),
            "crc": zlib.crc32(payload) & 0xFFFFFFFF,
        },
        separators=(",", ":"),
    ).encode("utf-8")
    return MAGIC + _LEN.pack(len(header)) + header + payload


def decode_completion(buf: bytes) -> dict:
    """Parse + validate one frame → dict(prompt, tokens, tenant,
    model_version). Raises :class:`DistillWireError` on anything torn."""
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        raise DistillWireError("frame must be bytes")
    buf = bytes(buf)
    if len(buf) < len(MAGIC) + _LEN.size or buf[: len(MAGIC)] != MAGIC:
        raise DistillWireError("bad distill frame magic")
    (hlen,) = _LEN.unpack_from(buf, len(MAGIC))
    if hlen > _MAX_HEADER:
        raise DistillWireError(f"header length {hlen} exceeds bound")
    start = len(MAGIC) + _LEN.size
    if len(buf) < start + hlen:
        raise DistillWireError("truncated distill header")
    try:
        header = json.loads(buf[start : start + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise DistillWireError(f"undecodable distill header: {exc}") from exc
    if not isinstance(header, dict) or header.get("v") != WIRE_VERSION:
        raise DistillWireError("unknown distill wire version")
    try:
        n_p, n_t = int(header["np"]), int(header["nt"])
        crc = int(header["crc"])
        mv = int(header["mv"])
        tenant = str(header["tenant"]).encode("latin-1")
    except (KeyError, TypeError, ValueError) as exc:
        raise DistillWireError(f"malformed distill header: {exc}") from exc
    if n_p < 0 or n_t < 0:
        raise DistillWireError("negative sequence length")
    payload = buf[start + hlen :]
    want = 4 * (n_p + n_t)
    if len(payload) != want:
        raise DistillWireError(
            f"payload length {len(payload)} != declared {want}"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise DistillWireError("distill payload CRC mismatch")
    ids = np.frombuffer(payload, dtype=np.int32)
    return {
        "prompt": ids[:n_p].copy(),
        "tokens": ids[n_p:].copy(),
        "tenant": tenant,
        "model_version": mv,
    }


def distill_processor(seq_len: int):
    """Per-record KafkaStream processor: frame → ``{"tokens": [S] int32,
    "mask": [S] int32}`` (prompt ++ committed tokens, left-aligned,
    truncated/zero-padded to ``seq_len``). Malformed frames → ``None``
    (the stream's drop signal) so one torn record never stalls training.
    """
    if seq_len < 2:
        raise ValueError("seq_len must be >= 2 (next-token loss shifts)")

    def process(record) -> dict | None:
        try:
            rec = decode_completion(record.value)
        except DistillWireError:
            return None
        seq = np.concatenate([rec["prompt"], rec["tokens"]])[:seq_len]
        n = seq.shape[0]
        toks = np.zeros(seq_len, np.int32)
        toks[:n] = seq
        mask = np.zeros(seq_len, np.int32)
        mask[:n] = 1
        return {"tokens": toks, "mask": mask}

    return process

"""DistillController — the closed loop's verdict: when does a refreshed
draft actually roll?

The spec servers already count acceptance on-device (accepted /
proposed, ``spec_stats``); the controller turns those CUMULATIVE
counters into a WINDOWED live-α gauge and gates draft refreshes on it:
refresh when (a) a newer draft version than the one applied is
available on the checkpoint plane, (b) the refresh cooldown has elapsed
(hysteresis — a refresh storm cannot thrash the fleet), and (c) either
the windowed α has degraded below ``drop_frac`` of the best window seen
since the last refresh (the drift signal) or ``refresh_on_publish``
says every new version rolls. Decisions are typed on the trace stream
(``draft_refresh``) and the clock is INJECTABLE — under a
``resilience.ManualClock`` the whole loop replays byte-identically,
which is what the hysteresis unit test pins.

Safety is by construction, not policy: ``swap_draft_params`` refreshes
only the PROPOSER — the target's verification commits tokens — so a
mid-serve refresh (no quiesce) can change α, never committed output.
The refresh-under-chaos differential asserts exactly that.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

from torchkafka_tpu.errors import CheckpointWireError

_logger = logging.getLogger("torchkafka_tpu.distill")


@dataclasses.dataclass(frozen=True)
class DistillPolicy:
    """Refresh gating knobs.

    ``window_rounds``: serve rounds folded into one α window.
    ``min_proposed``: proposals a window needs before its α counts (a
    near-idle window's α is noise, not signal).
    ``drop_frac``: refresh when α_window < drop_frac × α_best-since-
    last-refresh. 1.0 ⇒ any degradation triggers (given a new version).
    ``cooldown_s``: minimum seconds between APPLIED refreshes — the
    hysteresis floor.
    ``refresh_on_publish``: roll every newer published version once the
    cooldown allows, without requiring an α drop (the "always track the
    trainer" mode the closed-loop demo uses).
    """

    window_rounds: int = 32
    min_proposed: int = 64
    drop_frac: float = 0.8
    cooldown_s: float = 5.0
    refresh_on_publish: bool = False

    def __post_init__(self) -> None:
        if self.window_rounds < 1:
            raise ValueError("window_rounds must be >= 1")
        if self.min_proposed < 1:
            raise ValueError("min_proposed must be >= 1")
        if not 0.0 < self.drop_frac <= 1.0:
            raise ValueError("drop_frac must be in (0, 1]")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")


class DistillController:
    """Windowed α tracking + hysteretic refresh decisions.

    Feed ``note_round`` the fleet's CUMULATIVE accepted/proposed sums
    once per serve round and ``note_version`` each published draft
    version; poll ``maybe_refresh`` for a directive. The caller applies
    the swap and confirms with ``note_applied`` (or ``note_rejected``
    when the fetch-side CRC refused the checkpoint — that version is
    then skipped forever; a clean republish arrives as a NEW version).
    """

    def __init__(
        self,
        policy: DistillPolicy | None = None,
        *,
        applied_version: int = 0,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        metrics=None,
    ) -> None:
        self.policy = policy or DistillPolicy()
        self._clock = clock
        self._tracer = tracer
        self._metrics = metrics
        self.applied_version = int(applied_version)
        self.available_version = int(applied_version)
        self.alpha_window: float | None = None  # last CLOSED window's α
        self.alpha_best: float | None = None  # best window since refresh
        self._rounds = 0
        self._win_acc0 = 0  # cumulative counters at the window's open
        self._win_prop0 = 0
        self._last_acc = 0
        self._last_prop = 0
        self._last_refresh_t: float | None = None
        self._rejected: set[int] = set()
        self.refreshes = 0

    # ------------------------------------------------------------ inputs

    def note_round(self, accepted: int, proposed: int) -> None:
        """One serve round's CUMULATIVE fleet counters. Every
        ``window_rounds`` rounds the window closes: if it saw at least
        ``min_proposed`` proposals its α becomes the live gauge (and
        lifts α_best); a sparser window is discarded unmeasured."""
        self._last_acc, self._last_prop = int(accepted), int(proposed)
        self._rounds += 1
        if self._rounds % self.policy.window_rounds:
            return
        d_acc = self._last_acc - self._win_acc0
        d_prop = self._last_prop - self._win_prop0
        self._win_acc0, self._win_prop0 = self._last_acc, self._last_prop
        if d_prop < self.policy.min_proposed:
            return
        self.alpha_window = d_acc / d_prop
        if self.alpha_best is None or self.alpha_window > self.alpha_best:
            self.alpha_best = self.alpha_window
        if self._metrics is not None:
            self._metrics.spec_alpha_window.set(self.alpha_window)

    def note_version(self, version: int) -> None:
        """A draft checkpoint version is available on the plane."""
        if int(version) > self.available_version:
            self.available_version = int(version)

    # ---------------------------------------------------------- verdicts

    def _cooled_down(self) -> bool:
        if self._last_refresh_t is None:
            return True
        return (
            self._clock() - self._last_refresh_t >= self.policy.cooldown_s
        )

    def maybe_refresh(self) -> dict | None:
        """A refresh directive (``{"version", "reason", "alpha"}``) or
        None. Never fires twice for one version, never inside the
        cooldown, never for a CRC-rejected version."""
        v = self.available_version
        if v <= self.applied_version or v in self._rejected:
            return None
        if not self._cooled_down():
            return None
        if self.policy.refresh_on_publish:
            reason = "published"
        else:
            if (
                self.alpha_window is None
                or self.alpha_best is None
                or self.alpha_window
                >= self.policy.drop_frac * self.alpha_best
            ):
                return None
            reason = "alpha_drop"
        return {"version": v, "reason": reason, "alpha": self.alpha_window}

    def note_applied(self, version: int, reason: str = "alpha_drop") -> None:
        """The fleet rebound its drafts to ``version``: stamp the
        cooldown clock and RESET the α baseline — the post-refresh
        windows build a fresh best, so the old draft's peak can't hold
        the new one hostage."""
        self.applied_version = int(version)
        self._last_refresh_t = self._clock()
        self.alpha_best = None
        self.refreshes += 1
        if self._tracer is not None:
            self._tracer.draft_refresh(
                reason, int(version), alpha=self.alpha_window
            )
        if self._metrics is not None:
            self._metrics.draft_refreshes(reason).add(1)
            self._metrics.draft_version.set(float(version))
        _logger.info(
            "draft refreshed to version %d (%s, alpha_window=%s)",
            version, reason, self.alpha_window,
        )

    def note_rejected(self, version: int) -> None:
        """Fetch-side validation refused ``version`` (torn frames, CRC,
        tree drift): skip it permanently — a clean republish is a new
        version — and keep serving the incumbent draft."""
        self._rejected.add(int(version))
        if self._tracer is not None:
            self._tracer.draft_refresh("checkpoint_rejected", int(version))
        if self._metrics is not None:
            self._metrics.draft_refreshes("checkpoint_rejected").add(1)
        _logger.warning(
            "draft version %d rejected by checkpoint validation; "
            "keeping the incumbent", version,
        )


class InProcessDistillDriver:
    """Close the loop against an in-process ``ServingFleet``: per serve
    round, fold every replica's ``spec_stats`` into the controller's
    windowed α, and apply refresh directives by fetching the version
    from the checkpoint topic (CRC-validated against the incumbent
    draft's tree) and ``swap_draft_params``-ing every runnable replica
    between ticks — no quiesce, committed tokens invariant by the
    spec-decode contract.

    Plug ``on_round`` into ``fleet.serve(on_round=...)`` (compose it
    with a workload driver's hook by calling both). Version discovery
    is push-based: the trainer owner calls ``note_version`` (directly
    or via the controller) when a publish lands — the driver adds no
    polling of its own, so a no-trainer run costs two counter reads per
    round.
    """

    def __init__(
        self,
        fleet,
        controller: DistillController,
        *,
        broker=None,
        ckpt_topic: str | None = None,
        versions: dict | None = None,
    ) -> None:
        if (broker is None or ckpt_topic is None) and versions is None:
            raise ValueError(
                "need broker+ckpt_topic (wire delivery) or a versions "
                "dict (in-process delivery)"
            )
        self._fleet = fleet
        self._ctl = controller
        self._broker = broker
        self._ckpt_topic = ckpt_topic
        self._versions = versions

    @property
    def controller(self) -> DistillController:
        return self._ctl

    def note_version(self, version: int) -> None:
        self._ctl.note_version(version)

    def on_round(self, fleet, served: int) -> None:
        acc = prop = 0
        for rep in fleet.replicas:
            if not rep.runnable:
                continue
            stats = rep.gen.spec_stats()
            acc += stats["accepted"]
            prop += stats["proposed"]
        self._ctl.note_round(acc, prop)
        directive = self._ctl.maybe_refresh()
        if directive is not None:
            self._apply(directive)

    def _apply(self, directive: dict) -> None:
        version = directive["version"]
        live = [r for r in self._fleet.replicas if r.runnable]
        if not live:
            return
        try:
            if self._versions is not None:
                draft = self._versions[version]
            else:
                from torchkafka_tpu.source.checkpoint_wire import (
                    fetch_checkpoint,
                    rebuild_tree,
                )

                flat, _manifest = fetch_checkpoint(
                    self._broker, self._ckpt_topic, version
                )
                # The incumbent draft tree is the schema: shape/dtype
                # drift or missing arrays reject BEFORE any swap.
                draft = rebuild_tree(live[0].gen._draft_params, flat)
        except (CheckpointWireError, KeyError):
            self._ctl.note_rejected(version)
            return
        for rep in live:
            rep.gen.swap_draft_params(draft)
            if self._fleet.tracer is not None:
                self._fleet.tracer.draft_swapped(
                    version, member=f"replica-{rep.id}", replica=rep.id
                )
            rep.gen.metrics.draft_version.set(float(version))
            self._fleet.metrics.replica_draft_version(
                f"replica-{rep.id}"
            ).set(float(version))
        self._ctl.note_applied(version, directive["reason"])

"""run_distill_worker — the DistillTrainer as a real fleet process.

The ``role: "distill"`` sibling of ``fleet.proc.run_replica_worker``
and ``fleet.prefill.run_prefill_worker``: its own BrokerClient, its own
consumer group ``<group>-distill`` over the distill topic (heartbeat-
leased there — the supervisor's lease sweep fences and respawns it like
any other worker), training the layer-truncated draft on the committed
corpus and publishing versioned draft checkpoints the serving fleet's
DistillController picks up. Training is pumped in bounded step chunks
so fence/shutdown checks interleave with the jitted loop.

Crash discipline: the corpus group is at-least-once (offsets commit
after each step; a re-delivered record is one more gradient sample), a
death at ``distill_pre_publish`` loses at most ``publish_every`` steps
and never a committed token, and a torn checkpoint publish is rejected
by the fetch-side CRC — all three SIGKILL-matrixed.
"""

from __future__ import annotations

import json
import time


def run_distill_worker(spec: dict, broker=None, shutdown=None) -> int:
    from torchkafka_tpu.distill.trainer import DistillTrainer
    from torchkafka_tpu.errors import (
        BrokerUnavailableError,
        FencedMemberError,
    )
    from torchkafka_tpu.fleet.proc import _HeartbeatSender, build_model
    from torchkafka_tpu.serve import ServeMetrics
    from torchkafka_tpu.source.memory import MemoryConsumer

    EXIT_CLEAN, EXIT_FENCED = 0, 3
    own_client = broker is None
    if own_client:
        from torchkafka_tpu.resilience import RetryPolicy
        from torchkafka_tpu.source.netbroker import BrokerClient

        b = spec["broker"]
        broker = BrokerClient(
            b["host"], int(b["port"]),
            timeout_s=float(spec.get("connect_timeout_s", 30.0)),
            retry=RetryPolicy(
                max_attempts=int(spec.get("reconnect_attempts", 6)),
                base_delay_s=0.05, max_delay_s=1.0,
                deadline_s=float(spec.get("reconnect_deadline_s", 15.0)),
            ),
        )
    member = spec["member_id"]
    consumer = None
    hb = None
    trainer = None
    metrics = ServeMetrics()
    try:
        cfg, params = build_model(spec["model"])
        group = f"{spec['group']}-distill"
        consumer = MemoryConsumer(
            broker, spec["distill_topic"], group_id=group, member_id=member,
        )
        hb_interval = spec.get("heartbeat_interval_s", 0.25)
        if hb_interval is not None and spec.get(
            "heartbeat_mode", "thread"
        ) == "thread":
            hb = _HeartbeatSender(consumer, float(hb_interval))
            hb.start()
        trainer = DistillTrainer(
            consumer, params, cfg,
            seq_len=int(
                spec.get("distill_seq_len")
                or int(spec["prompt_len"]) + int(spec["max_new"])
            ),
            batch_size=int(spec.get("distill_batch", 8)),
            draft_layers=spec.get("draft_layers"),
            learning_rate=float(spec.get("distill_lr", 1e-3)),
            broker=broker,
            ckpt_topic=spec.get("ckpt_topic"),
            publish_every=int(spec.get("publish_every", 0)),
            base_version=int(spec.get("draft_base_version", 0)),
            metrics=metrics,
        )
        if spec.get("ready_topic"):
            from torchkafka_tpu.source.producer import MemoryProducer

            MemoryProducer(broker).send(
                spec["ready_topic"], member.encode()
            )
        idle_exit_ms = spec.get("idle_exit_ms")
        chunk = int(spec.get("distill_chunk_steps", 4))
        idle_since = None
        while True:
            if shutdown is not None and shutdown.requested:
                return EXIT_CLEAN
            if hb is not None and hb.fenced:
                raise FencedMemberError(f"distill member {member!r} fenced")
            if hb is not None and hb.error is not None:
                raise hb.error
            before = trainer.steps
            try:
                if hb is None and hb_interval is not None:
                    consumer.heartbeat()
                trainer.run(max_steps=chunk, idle_timeout_ms=100)
            except BrokerUnavailableError:
                time.sleep(0.02)
                continue
            if trainer.steps > before:
                idle_since = None
            else:
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                elif (
                    idle_exit_ms is not None
                    and (now - idle_since) * 1e3 >= idle_exit_ms
                ):
                    return EXIT_CLEAN
                time.sleep(0.002)
    except FencedMemberError:
        return EXIT_FENCED
    finally:
        if hb is not None:
            hb.stop()
        if trainer is not None and spec.get("metrics_path"):
            try:
                doc = {
                    "member": member,
                    "role": "distill",
                    **trainer.report(),
                }
                tmp = spec["metrics_path"] + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f)
                import os

                os.replace(tmp, spec["metrics_path"])
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if consumer is not None:
            try:
                consumer.close()
            except Exception:  # noqa: BLE001
                pass
        if own_client:
            try:
                broker.close()
            except Exception:  # noqa: BLE001
                pass

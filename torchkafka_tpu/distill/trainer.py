"""DistillTrainer — fine-tune the speculative draft on live committed
traffic.

The repo's two halves fused: the TRAINING plane (KafkaStream +
make_train_step, the commit-after-step loop) pointed at the SERVING
plane's own output — the distill topic of committed (prompt, tokens)
frames the exactly-once publisher stages inside its commit windows. The
draft starts as the target's layer-truncated tree
(``models.spec_decode.truncated_draft`` — the same construction
SpecStreamingGenerator self-drafts with, so the trained tree swaps
straight into a serving fleet via ``swap_draft_params`` with zero
recompilation), trains with next-token CE on the committed sequences,
and every ``publish_every`` steps publishes a VERSIONED draft
checkpoint onto the checkpoint topic (``source.checkpoint_wire`` —
CRC'd manifest + chunks, so a torn publish is rejected fetch-side and
the fleet keeps its incumbent).

Determinism contract (the trainer-loop differential test): the stream
runs synchronous (``prefetch=0`` — no threads), the optimizer math is
jitted pure functions, and the draft init derives from a seed — same
seed + same topic contents ⇒ byte-identical draft params, step for
step. At-least-once consumption is SAFE here (unlike serving): a
re-delivered corpus record is just one more gradient sample, so the
trainer commits its offsets after each step and resumes from its own
consumer group's offsets after a crash
(``crash_hook("distill_pre_publish")`` is the matrixed death point —
between a train step and the checkpoint publish, where the loss is
maximal and must still be zero committed-token impact).
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from torchkafka_tpu.distill.wire import distill_processor
from torchkafka_tpu.models.spec_decode import truncated_draft
from torchkafka_tpu.models.transformer import make_train_step
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source.checkpoint_wire import publish_checkpoint

_logger = logging.getLogger("torchkafka_tpu.distill")


class DistillTrainer:
    """Consume the distill topic, train the draft, publish versions.

    ``params``/``cfg``: the TARGET model — the draft is derived as its
    ``draft_layers``-truncated tree unless an explicit
    ``draft_params``/``draft_cfg`` pair is given. Weight-sharing note:
    ``truncated_draft`` aliases embed/ln_f/lm_head BY REFERENCE, and the
    jitted train step DONATES its params argument — so the trainer deep-
    copies every draft leaf at init. Without the copy, the first step
    would delete the serving target's own buffers out from under it.

    ``publish_every`` > 0: every that-many steps, publish the current
    draft as version ``base_version + publishes-so-far + 1`` onto
    ``ckpt_topic`` (requires ``broker``). Versions are MONOTONIC per
    trainer; a fleet's DistillController refreshes only to versions
    newer than what it applied, so an at-least-once republish after a
    crash is harmless.
    """

    def __init__(
        self,
        consumer,
        params,
        cfg,
        *,
        seq_len: int,
        batch_size: int = 8,
        draft_layers: int | None = None,
        draft_params=None,
        draft_cfg=None,
        mesh=None,
        optimizer=None,
        learning_rate: float = 1e-3,
        broker=None,
        ckpt_topic: str | None = None,
        publish_every: int = 0,
        base_version: int = 0,
        metrics=None,
    ) -> None:
        import optax

        from torchkafka_tpu.parallel.mesh import make_mesh

        if publish_every < 0:
            raise ValueError("publish_every must be >= 0")
        if publish_every and (broker is None or ckpt_topic is None):
            raise ValueError(
                "publish_every requires broker and ckpt_topic (the "
                "checkpoint plane the refreshed drafts ship on)"
            )
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "draft_params and draft_cfg must be given together"
            )
        self._consumer = consumer
        self._seq_len = int(seq_len)
        self._batch_size = int(batch_size)
        # Default mesh: ONE device, regardless of how many the host
        # exposes — the draft is tiny and a single-chip trainer keeps
        # the batch math (and thus the differential test) independent
        # of the serving fleet's device topology.
        self._mesh = (
            mesh
            if mesh is not None
            else make_mesh({"data": 1}, devices=jax.devices()[:1])
        )
        if draft_params is None:
            n = draft_layers or max(1, cfg.n_layers // 2)
            draft_params, draft_cfg = truncated_draft(params, cfg, n)
        if seq_len > draft_cfg.max_seq_len:
            raise ValueError(
                f"seq_len {seq_len} exceeds draft max_seq_len "
                f"{draft_cfg.max_seq_len}"
            )
        self.draft_cfg = draft_cfg
        optimizer = optimizer or optax.adamw(learning_rate)
        _init, self._step_fn = make_train_step(
            draft_cfg, self._mesh, optimizer
        )
        # The draft tree from truncated_draft matches init_params'
        # structure for draft_cfg, so the optimizer inits directly over
        # it — the trained tree stays swap-compatible with a serving
        # SpecStreamingGenerator built on the same geometry. jnp.copy
        # (not device_put, which may alias in place) severs the embed/
        # ln_f/lm_head sharing with the target before donation sees it.
        import jax.numpy as jnp

        self.draft_params = jax.tree_util.tree_map(jnp.copy, draft_params)
        self._opt_state = optimizer.init(self.draft_params)
        self._broker = broker
        self._ckpt_topic = ckpt_topic
        self._publish_every = int(publish_every)
        self._base_version = int(base_version)
        self._metrics = metrics
        self.steps = 0
        self.records = 0
        self.published = 0
        self.last_loss: float | None = None

    @property
    def next_version(self) -> int:
        return self._base_version + self.published + 1

    def publish(self) -> int:
        """Publish the current draft as the next version; returns it.
        The crash point sits BETWEEN the trained state and the publish —
        death here loses at most ``publish_every`` steps of progress
        (the next incarnation re-trains from its committed offsets and
        publishes the same version number), never a committed token."""
        version = self.next_version
        crash_hook("distill_pre_publish")
        host = jax.tree_util.tree_map(np.asarray, self.draft_params)
        publish_checkpoint(
            self._broker, self._ckpt_topic, version, host, kind="draft"
        )
        self.published += 1
        _logger.info(
            "published draft version %d after %d steps", version, self.steps
        )
        return version

    def run(
        self,
        max_steps: int | None = None,
        *,
        idle_timeout_ms: int = 500,
        shutdown=None,
    ) -> dict:
        """Train until the topic idles (``idle_timeout_ms`` with no new
        corpus records), ``max_steps`` land, or ``shutdown`` fires.
        Returns a report dict. Re-entrant: call again to resume on the
        same consumer group offsets — the loop commits after each step
        (commit-after-step, the training plane's standing contract)."""
        import jax.numpy as jnp

        from torchkafka_tpu.pipeline.stream import KafkaStream

        steps_in = self.steps
        stream = KafkaStream(
            self._consumer,
            distill_processor(self._seq_len),
            batch_size=self._batch_size,
            mesh=self._mesh,
            # Synchronous + padded: no prefetch thread (determinism by
            # construction) and a final ragged batch still trains.
            prefetch=0,
            pad_policy="pad",
            idle_timeout_ms=idle_timeout_ms,
            owns_consumer=False,
        )
        try:
            for batch, token in stream:
                if shutdown is not None and getattr(
                    shutdown, "requested", False
                ):
                    break
                tokens = batch.data["tokens"]
                # Row mask: frame-level positions AND batch padding rows.
                mask = batch.data["mask"] * jnp.asarray(
                    batch.valid_mask()[:, None].astype(np.int32)
                )
                self.draft_params, self._opt_state, loss = self._step_fn(
                    self.draft_params, self._opt_state, tokens, mask
                )
                token.commit(wait_for=loss)
                self.steps += 1
                self.records += int(batch.valid_count)
                self.last_loss = float(loss)
                if self._metrics is not None:
                    self._metrics.distill_steps.add(1)
                    self._metrics.distill_records.add(int(batch.valid_count))
                if (
                    self._publish_every
                    and self.steps % self._publish_every == 0
                ):
                    self.publish()
                if max_steps is not None and (
                    self.steps - steps_in
                ) >= max_steps:
                    break
        finally:
            stream.close()
        return self.report()

    def report(self) -> dict:
        return {
            "steps": self.steps,
            "records": self.records,
            "published": self.published,
            "next_version": self.next_version,
            "loss": self.last_loss,
            "draft_layers": self.draft_cfg.n_layers,
        }

"""Circuit breaker: stop hammering a broker that is provably down.

Retries handle the *blip*; the breaker handles the *outage*. Once N
consecutive operations have failed with retryable faults, the broker is
evidently unavailable and every further attempt is pure cost — latency
added to the serving hot loop, connection churn added to a broker trying
to recover. The breaker converts that into a fast local decision:

- **closed** (healthy): every operation is allowed; consecutive failures
  are counted, successes reset the count.
- **open** (outage declared): operations are refused locally (``allow()``
  is False) for ``reset_timeout_s`` — callers degrade (empty polls,
  fast-failed commits) instead of blocking on a dead socket.
- **half-open** (probing): after the cooldown, exactly
  ``half_open_probes`` operations are let through as probes. One success
  closes the circuit; one failure re-opens it and restarts the cooldown.

The state machine is deliberately the textbook one (Nygard's *Release
It!* shape, the same three states Polly/resilience4j implement) because
its value here is *observability*: ``opens``/``closes``/``probes``
counters and a numeric ``state_code`` export through the resilience
metrics, so "circuit opened at 12:03, closed at 12:07" is a dashboard
fact, not a log archaeology project. Time is injectable for the same
reason as everywhere else in this layer: chaos tests drive the cooldown
with a ManualClock and stay deterministic.

Thread-safe; shared by the poll path (stream producer thread) and the
commit path (stream owner's thread) of one consumer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: Gauge encoding for dashboards: healthy=0, probing=0.5, outage=1.
_STATE_CODES = {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}


class CircuitBreaker:
    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if reset_timeout_s <= 0:
            raise ValueError(f"reset_timeout_s must be > 0, got {reset_timeout_s}")
        if half_open_probes < 1:
            raise ValueError(f"half_open_probes must be >= 1, got {half_open_probes}")
        self._threshold = failure_threshold
        self._reset_timeout_s = reset_timeout_s
        self._half_open_probes = half_open_probes
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.opens = 0  # transitions into OPEN (first open + every re-open)
        self.closes = 0  # transitions into CLOSED from HALF_OPEN
        self.probes = 0  # operations admitted while HALF_OPEN

    # ----------------------------------------------------------- inspection

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek()

    @property
    def state_code(self) -> float:
        with self._lock:
            return _STATE_CODES[self._peek()]

    def _peek(self) -> str:
        """State with the cooldown applied (an expired OPEN reads as
        HALF_OPEN even before the next allow() formalizes it)."""
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self._reset_timeout_s
        ):
            return HALF_OPEN
        return self._state

    # ------------------------------------------------------------ decisions

    def allow(self) -> bool:
        """May the caller attempt an operation right now? OPEN refuses
        until the cooldown elapses; HALF_OPEN admits up to
        ``half_open_probes`` concurrent probes."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at < self._reset_timeout_s:
                    return False
                self._state = HALF_OPEN
                self._probes_in_flight = 0
            if self._state == HALF_OPEN:
                if self._probes_in_flight >= self._half_open_probes:
                    return False
                self._probes_in_flight += 1
                self.probes += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probes_in_flight = 0
                self.closes += 1

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # The probe failed: the outage is not over. Re-open and
                # restart the cooldown from now.
                self._open()
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and (
                self._consecutive_failures >= self._threshold
            ):
                self._open()

    def _open(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._probes_in_flight = 0
        self.opens += 1

"""ResilientConsumer: the transport hardened against transient faults.

A ``Consumer`` wrapper (the same duck-typed protocol ``ChaosConsumer``
wraps, source/chaos.py) that makes the two broker round-trips on the hot
path — ``poll`` and ``commit`` — survive the faults a production broker
actually throws: connection resets, request timeouts, leadership
elections, whole-broker outages. Everything else (seek, pause, lag,
assignment, close) forwards verbatim; those are control-plane calls whose
failures the caller should see.

Degradation ladder (policy.py + breaker.py do the deciding):

1. **Retry** — a retryable fault (errors.py classification) inside
   ``poll``/``commit`` is retried with full-jitter backoff until the
   policy's attempt or deadline budget runs out. Safe because both
   operations are idempotent: polls re-fetch from the consumer position,
   commits carry absolute next-read offsets.
2. **Degrade** — a poll that exhausts its budget returns ``[]`` (exactly
   what a slow broker looks like), so ``KafkaStream`` idles and the
   serving fleet keeps ticking in-flight generation slots instead of
   crashing; a commit that exhausts its budget raises
   ``CommitFailedError`` — the one failure every commit caller already
   treats as survivable (the reference's contract,
   /root/reference/src/kafka_dataset.py:131-135): nothing was committed,
   the records re-deliver.
3. **Break** — after ``failure_threshold`` consecutive faults the
   circuit opens: polls and commits fail fast locally (no broker I/O,
   counted as *suppressed*) until the cooldown elapses, then a half-open
   probe decides recovery. While open, the consumer is a clean "no data,
   no commits" citizen — the invariant holder, because an uncommitted
   watermark can only ever cause re-delivery, never loss.

Terminal errors (``ConsumerClosedError``, ``NotAssignedError``, a genuine
rebalance ``CommitFailedError``) propagate untouched on the first throw —
retrying them is at best useless and at worst hides a bug.

Everything is observable through ``metrics`` (utils/metrics.py
``ResilienceMetrics``: retries, faults, degraded/suppressed ops, circuit
transitions + state gauge) and deterministic under test: inject a seeded
policy and a ``ManualClock`` and the whole retry/break/probe schedule
replays exactly.
"""

from __future__ import annotations

import logging
from typing import Mapping

from torchkafka_tpu.errors import CommitFailedError
from torchkafka_tpu.resilience.breaker import OPEN, CircuitBreaker
from torchkafka_tpu.resilience.policy import RetryPolicy
from torchkafka_tpu.source.consumer import Consumer, ConsumerIterMixin
from torchkafka_tpu.source.records import Record, TopicPartition
from torchkafka_tpu.utils.metrics import ResilienceMetrics

_logger = logging.getLogger(__name__)


class ResilientConsumer(ConsumerIterMixin):
    """Wrap any Consumer with retry/backoff, circuit breaking, and
    degraded modes on the poll/commit hot path.

    ``policy``: a RetryPolicy (default: 6 attempts, 50ms base full-jitter
    backoff capped at 2s, 30s per-operation deadline, retrying
    ``BrokerUnavailableError`` and anything self-declared retryable).
    ``breaker``: a CircuitBreaker (default: opens after 5 consecutive
    faults, 30s cooldown, 1 half-open probe) — constructed on the
    policy's clock so one ManualClock drives the whole stack in tests.
    """

    def __init__(
        self,
        inner: Consumer,
        *,
        policy: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        metrics: ResilienceMetrics | None = None,
    ) -> None:
        self._inner = inner
        self._policy = policy or RetryPolicy()
        self._breaker = breaker or CircuitBreaker(clock=self._policy.clock)
        self.metrics = metrics or ResilienceMetrics()
        # Last breaker state mirrored into metrics — plain attrs, so the
        # per-op happy path compares ints instead of taking RateMeter
        # locks (this sync runs on EVERY poll/commit; measured in
        # benchmarks/bench_pod.py --overhead).
        self._seen_opens = 0
        self._seen_closes = 0
        self._seen_state = 0.0

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def _sync_breaker_metrics(self) -> None:
        """Mirror the breaker's transition counters + state gauge into the
        metrics set, so a snapshot alone proves open-then-closed."""
        b, m = self._breaker, self.metrics
        # Unlocked int reads are safe here: opens/closes only grow, and a
        # missed increment is picked up on the next op's sync.
        d = b.opens - self._seen_opens
        if d > 0:
            self._seen_opens = b.opens
            m.circuit_opens.add(d)
            _logger.warning(
                "circuit OPEN after consecutive transport faults; "
                "degrading (empty polls, fast-failed commits)"
            )
        d = b.closes - self._seen_closes
        if d > 0:
            self._seen_closes = b.closes
            m.circuit_closes.add(d)
            _logger.info("circuit CLOSED: broker recovered")
        code = b.state_code
        if code != self._seen_state:
            self._seen_state = code
            m.circuit_state.set(code)

    # -------------------------------------------------------------- hot path

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        if not self._breaker.allow():
            self.metrics.suppressed_polls.add(1)
            self._sync_breaker_metrics()
            return []
        policy = self._policy
        start = policy.clock()
        attempt = 0
        while True:
            try:
                records = self._inner.poll(
                    max_records=max_records, timeout_ms=timeout_ms
                )
            except BaseException as exc:  # noqa: BLE001 - classified below
                if not policy.classify(exc):
                    # Terminal (closed consumer, protocol errors, bugs): not
                    # a transport fault, so it must not feed outage
                    # detection — and it must RESOLVE an in-flight half-open
                    # probe, or the breaker would wedge with a probe slot
                    # forever occupied.
                    self._breaker.record_success()
                    raise
                self.metrics.poll_faults.add(1)
                self._breaker.record_failure()
                attempt += 1
                delay = policy.backoff_s(attempt - 1)
                if (
                    self._breaker.state == OPEN
                    or attempt >= policy.max_attempts
                    or (
                        policy.deadline_s is not None
                        and (policy.clock() - start) + delay
                        >= policy.deadline_s
                    )
                ):
                    # Degrade, don't crash: an empty poll is exactly what a
                    # slow broker looks like — streams idle, fleets keep
                    # ticking in-flight slots, the watermark stays put.
                    self.metrics.degraded_polls.add(1)
                    self._sync_breaker_metrics()
                    return []
                self.metrics.retries.add(1)
                policy.sleep(delay)
                continue
            self._breaker.record_success()
            self._sync_breaker_metrics()
            return records

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        if not self._breaker.allow():
            self.metrics.suppressed_commits.add(1)
            self._sync_breaker_metrics()
            # The survivable spelling of "not now": nothing was committed,
            # every caller already treats this as re-delivery, and the
            # broker gets zero load while the circuit is open.
            raise CommitFailedError(
                "circuit open (broker outage declared): commit fast-failed "
                "without committing; offsets stay uncommitted and re-deliver"
            )
        policy = self._policy
        start = policy.clock()
        attempt = 0
        while True:
            try:
                self._inner.commit(offsets)
            except BaseException as exc:  # noqa: BLE001 - classified below
                if not policy.classify(exc):
                    # Incl. a genuine rebalance CommitFailedError: the
                    # broker RESPONDED (protocol rejection, not transport
                    # fault) — resolve any probe, don't count an outage.
                    self._breaker.record_success()
                    raise
                self.metrics.commit_faults.add(1)
                self._breaker.record_failure()
                attempt += 1
                delay = policy.backoff_s(attempt - 1)
                if (
                    self._breaker.state == OPEN
                    or attempt >= policy.max_attempts
                    or (
                        policy.deadline_s is not None
                        and (policy.clock() - start) + delay
                        >= policy.deadline_s
                    )
                ):
                    self._sync_breaker_metrics()
                    raise CommitFailedError(
                        "retry budget exhausted committing through a broker "
                        "fault; offsets stay uncommitted and re-deliver"
                    ) from exc
                self.metrics.retries.add(1)
                policy.sleep(delay)
                continue
            self._breaker.record_success()
            self._sync_breaker_metrics()
            return

    # --------------------------------------------- control plane: forwarded

    def committed(self, tp: TopicPartition) -> int | None:
        return self._inner.committed(tp)

    def position(self, tp: TopicPartition) -> int:
        return self._inner.position(tp)

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._inner.seek(tp, offset)

    def assignment(self):
        return self._inner.assignment()

    def offsets_for_times(self, times):
        return self._inner.offsets_for_times(times)

    def end_offsets(self, tps):
        return self._inner.end_offsets(tps)

    def lag(self):
        return self._inner.lag()

    def pause(self, *tps: TopicPartition) -> None:
        self._inner.pause(*tps)

    def resume(self, *tps: TopicPartition) -> None:
        self._inner.resume(*tps)

    def paused(self):
        return self._inner.paused()

    def has_paused(self) -> bool:
        fn = getattr(self._inner, "has_paused", None)
        return bool(self._inner.paused()) if fn is None else fn()

    def close(self) -> None:
        self._inner.close()

    def heartbeat(self):
        """Forward the lease renewal verbatim (transport retry lives in
        the inner client; a FencedMemberError must surface untouched —
        retrying a fenced member's heartbeat is a zombie's hope)."""
        fn = getattr(self._inner, "heartbeat", None)
        return None if fn is None else fn()

    # Group metadata (transactional offset commits present it so the
    # broker fences them generation-checked): forwarded where the inner
    # transport has it, None where it does not.

    @property
    def group_id(self):
        return getattr(self._inner, "group_id", None)

    @property
    def member_id(self):
        return getattr(self._inner, "member_id", None)

    @property
    def generation(self):
        return getattr(self._inner, "generation", None)

    # Iteration via ConsumerIterMixin over SELF.poll so the record-at-a-time
    # loop shape rides the resilient path too (same pattern as ChaosConsumer:
    # delegating to iter(inner) would bypass every retry).

    @property
    def _closed(self) -> bool:
        return bool(getattr(self._inner, "_closed", False))

    @property
    def _consumer_timeout_ms(self):
        return getattr(self._inner, "_consumer_timeout_ms", None)

    @property
    def _last_yielded(self):
        return getattr(self._inner, "_last_yielded", None)

"""Named crash-point injection: deterministic process death at chosen
fault windows.

The resilience layer's chaos tools so far (source/chaos.py's seeded
consumer/producer faults, fleet.ReplicaChaos's cooperative "kill") inject
faults at the TRANSPORT and SCHEDULER level. What they cannot express is
arbitrary *process death at a specific instruction boundary* — the window
between a poll and its ledger registration, between an output flush and
the offset commit it gates, mid-way through a journal or checkpoint
write. Those windows are exactly where at-least-once arguments live or
die, so each one is a NAMED crash point:

========================== =================================================
point                      window it pins
========================== =================================================
post_poll                  records fetched, nothing registered/committed —
                           death here must redeliver them verbatim
pre_commit                 outputs durable, offsets NOT yet committed —
                           death here replays (duplicates), never loses
post_commit_pre_checkpoint offsets committed, the paired checkpoint not yet
                           saved — resume must seek BACK to the checkpoint
mid_tick                   a decode tick block landed, completions not yet
                           retired — in-flight state dies with the process
post_dlq_pre_retire        a poison record's DLQ copy is durable but its
                           offset not yet retired — redelivery must
                           re-quarantine idempotently, never double-count
journal_mid_write          death inside the decode journal's tmp write —
                           the torn tmp must be invisible to recovery
checkpoint_mid_write       death after the checkpoint payload, before the
                           atomic rename — the torn step must be invisible
heartbeat_pre_send         a replica made decode progress but dies before
                           the lease renewal that would prove it alive —
                           the lease expires, survivors absorb its
                           partitions, its uncommitted work re-delivers
lease_expired_pre_fence    a supervisor OBSERVED an expired lease but dies
                           before fencing — the zombie stays a member, yet
                           its next commit self-fences (commit-time reap),
                           so the watermark never merges zombie work
journal_handoff_pre_load   a replica (or recovery incarnation) dies inside
                           the peer-journal scan, before hints load — the
                           journals on disk stay intact; the next scan
                           warm-resumes exactly the same entries
txn_begin_post             a transaction is open on the broker, nothing
                           produced in it — the next incarnation's
                           init_producer_id fences the epoch and aborts it;
                           recovery must leave NO trace in the committed view
txn_produce_mid            some of a commit window's outputs are in the open
                           transaction, the rest never will be — none may
                           surface committed; recovery re-serves the whole
                           window exactly once
txn_pre_commit             records + offsets staged, commit_txn not yet
                           issued — the exactly-once twin of pre_commit:
                           death aborts, recovery's committed view holds
                           each output ONCE (vs at-least-once's duplicates)
txn_post_commit_pre_ack    the transaction committed ON the broker but the
                           producer dies before observing the ack — offsets
                           moved atomically with the records, so recovery
                           re-serves NOTHING; the committed view already
                           holds the single copy
wal_append_mid             the BROKER dies between the two halves of a WAL
                           frame's body — the torn tail. The event was never
                           acknowledged; recovery must CRC-detect the frame,
                           truncate it away, and never replay it
wal_pre_fsync              a WAL frame is fully written but not yet fsynced —
                           process death keeps it (page cache), machine death
                           may not; either outcome must satisfy the same
                           invariants (the event was unacknowledged)
txn_marker_pre_append      a transaction's offsets validated, the commit
                           marker NOT yet in the WAL — broker death here
                           means recovery finds a begun-but-unsettled
                           transaction and ABORTS it; nothing surfaces
                           committed
txn_marker_post_append_pre_ack  the commit marker is durably in the WAL but
                           the broker dies before flipping memory state /
                           acking — recovery REPLAYS the marker (records +
                           offsets commit atomically) and the producer's
                           retry is answered idempotently
recovery_mid_replay        the recovering broker dies mid-way through its
                           own WAL replay — replay is read-only until it
                           completes, so a second recovery must reproduce
                           the identical state
prefill_handoff_pre_publish a disaggregated PREFILL worker filled a
                           prompt's KV blocks but dies before publishing
                           the handoff — no decode replica ever sees it;
                           the prompt must fall back to a local prefill
                           (at-least-once; exactly-once mode: committed
                           duplicates stay 0) and the prefill group's
                           offset must re-deliver to the next incarnation
decode_adopt_pre_activate  a decode replica uploaded an adopted handoff's
                           KV payload into its pool but dies before
                           activating the slot — the record was never
                           emitted to the ledger, so it re-delivers and
                           re-adopts (or re-prefills) byte-identically
scale_up_pre_spawn         the SUPERVISOR decided a scale-up target and
                           chose the new member's replica-index slot but
                           dies before spawning it — no half-born member
                           exists, the group is untouched; a recovery
                           supervisor re-applies the controller target and
                           the fleet converges with zero lost
scale_down_mid_drain       the SUPERVISOR SIGTERMed a scale-down victim
                           but dies before recording the drain — the
                           victim's own drain discipline (finish, commit,
                           leave) still holds whatever the broker's fate
                           allows; nothing uncommitted is lost, and a
                           recovery supervisor converges to the target
repl_frame_pre_ship        the LEADER appended a frame to its own WAL but
                           dies before shipping it to any follower — the
                           mutation was never quorum-acked, so the client
                           retries against the promoted follower; the
                           leader-local-only frame must never surface in
                           the cell's committed view as a duplicate
repl_frame_post_majority_pre_ack  a majority of replicas hold the frame
                           but the leader dies before acking the client —
                           the mutation IS durable cell-wide; promotion
                           replays it and the client's retry is answered
                           idempotently (the exactly-once twin of
                           txn_marker_post_append_pre_ack, one layer up)
election_pre_promote       an election chose the winning follower but the
                           process dies before the promotion replay /
                           port takeover — the cell stays leaderless; a
                           re-run election (epoch bumped again) must
                           converge on the same durable prefix
rollout_pre_swap           a replica quiesced for a weight swap (in-flight
                           drained, window committed) but dies BEFORE the
                           journal records the new version — recovery
                           restarts on the OLD weights, the rollout
                           directive still stands, and the re-swap
                           converges to the controller target
swap_mid_apply             the journal durably records the NEW version but
                           the process dies before the in-memory param
                           rebind — the journal is EMPTY here (quiesced),
                           so recovery fetches and serves the new version;
                           no output was ever produced by mixed weights
canary_pre_verdict         the canary replica finished its shadow slice
                           but dies before publishing the verdict — no
                           swap happened anywhere; recovery re-runs the
                           canary deterministically and the rollout
                           proceeds (or rolls back) on the same evidence
distill_pre_publish        the distill trainer finished a train step but
                           dies before publishing the refreshed draft
                           checkpoint — no complete version ever appears
                           on the checkpoint topic (a torn frame set is
                           rejected by the fetch-side CRC path), the
                           trainer's own consumer offsets re-deliver its
                           uncommitted corpus at-least-once, and the
                           serving fleet's committed tokens are untouched
                           (the trainer is off the serving path)
draft_swap_pre_apply       a speculative server fetched and validated a
                           refreshed draft but dies before rebinding it —
                           the draft only PROPOSES and verification
                           commits, so the committed view at death is a
                           prefix of the no-refresh reference; recovery
                           serves byte-identical tokens on either draft
========================== =================================================

Sites call ``crash_hook("<name>")``; production cost is one global ``is
None`` check. Tests arm a point with ``arm()`` (in-process, ``mode=
"raise"``) or via the ``TORCHKAFKA_CRASHPOINT`` environment variable in a
subprocess (``mode="kill"`` → SIGKILL, a real unclean death). Injection
is DETERMINISTIC: the Nth arrival at the armed point fires, every other
arrival is free — so a crash matrix can replay the same death precisely.

The registry is closed: ``crash_hook`` rejects unregistered names, so a
typo'd site cannot silently never fire, and the crash-matrix test can
assert REGISTERED_CRASH_POINTS ⊆ points-actually-killed-at (a registered
point the matrix does not cover fails the suite).
"""

from __future__ import annotations

import os
import signal
import threading

from torchkafka_tpu.errors import TpuKafkaError

# The closed set of instrumented crash windows. Adding a site means adding
# its name HERE first — and the crash matrix (tests/test_crash_matrix.py)
# fails until the new point is exercised by a real subprocess kill.
REGISTERED_CRASH_POINTS: tuple[str, ...] = (
    "post_poll",
    "pre_commit",
    "post_commit_pre_checkpoint",
    "mid_tick",
    "post_dlq_pre_retire",
    "journal_mid_write",
    "checkpoint_mid_write",
    "heartbeat_pre_send",
    "lease_expired_pre_fence",
    "journal_handoff_pre_load",
    "txn_begin_post",
    "txn_produce_mid",
    "txn_pre_commit",
    "txn_post_commit_pre_ack",
    "wal_append_mid",
    "wal_pre_fsync",
    "txn_marker_pre_append",
    "txn_marker_post_append_pre_ack",
    "recovery_mid_replay",
    "prefill_handoff_pre_publish",
    "decode_adopt_pre_activate",
    "scale_up_pre_spawn",
    "scale_down_mid_drain",
    "repl_frame_pre_ship",
    "repl_frame_post_majority_pre_ack",
    "election_pre_promote",
    "rollout_pre_swap",
    "swap_mid_apply",
    "canary_pre_verdict",
    "distill_pre_publish",
    "draft_swap_pre_apply",
)

ENV_VAR = "TORCHKAFKA_CRASHPOINT"


class CrashPointInjected(TpuKafkaError):
    """Raised by an armed crash point in ``mode="raise"`` — the in-process
    stand-in for death, used where a test wants the stack intact (torn
    checkpoint writes) rather than a subprocess. Terminal by definition:
    retrying the crashed operation is the recovery path's job."""


class _Armed:
    __slots__ = ("point", "at", "mode", "marker", "count", "lock")

    def __init__(self, point: str, at: int, mode: str, marker: str | None):
        self.point = point
        self.at = at
        self.mode = mode
        self.marker = marker
        self.count = 0
        self.lock = threading.Lock()


_armed: _Armed | None = None


def arm(
    point: str, *, at: int = 1, mode: str = "raise",
    marker: str | None = None,
) -> None:
    """Arm ``point`` to fire at its ``at``-th arrival.

    ``mode="raise"`` raises ``CrashPointInjected`` (in-process tests);
    ``mode="kill"`` SIGKILLs the process — no handlers, no atexit, no
    flushes, the honest crash. ``marker``: a file path written atomically
    just before firing, so a parent process can prove the point was
    actually reached (a SIGKILL'd child cannot report anything after)."""
    global _armed
    if point not in REGISTERED_CRASH_POINTS:
        raise ValueError(
            f"unknown crash point {point!r}; registered: "
            f"{REGISTERED_CRASH_POINTS}"
        )
    if at < 1:
        raise ValueError(f"at must be >= 1, got {at}")
    if mode not in ("raise", "kill"):
        raise ValueError(f"mode must be 'raise' or 'kill', got {mode!r}")
    _armed = _Armed(point, at, mode, marker)


def disarm() -> None:
    global _armed
    _armed = None


def armed_point() -> str | None:
    return _armed.point if _armed is not None else None


def arm_from_env(environ=os.environ) -> bool:
    """Arm from ``TORCHKAFKA_CRASHPOINT=point:at:mode[:marker_path]`` —
    the subprocess side of the crash matrix. Returns True if armed."""
    spec = environ.get(ENV_VAR)
    if not spec:
        return False
    parts = spec.split(":", 3)
    if len(parts) < 3:
        raise ValueError(
            f"{ENV_VAR} must be 'point:at:mode[:marker]', got {spec!r}"
        )
    point, at, mode = parts[0], int(parts[1]), parts[2]
    marker = parts[3] if len(parts) > 3 else None
    arm(point, at=at, mode=mode, marker=marker)
    return True


def _write_marker(path: str, point: str, count: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{point}:{count}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def crash_hook(point: str) -> None:
    """The site-side call. Free when nothing is armed (one global load);
    rejects unregistered names so sites cannot drift out of the registry."""
    armed = _armed
    if armed is None:
        if point not in REGISTERED_CRASH_POINTS:
            raise ValueError(
                f"crash_hook called with unregistered point {point!r}"
            )
        return
    if point not in REGISTERED_CRASH_POINTS:
        raise ValueError(
            f"crash_hook called with unregistered point {point!r}"
        )
    if point != armed.point:
        return
    with armed.lock:
        armed.count += 1
        fire = armed.count == armed.at
    if not fire:
        return
    if armed.marker:
        _write_marker(armed.marker, point, armed.at)
    if armed.mode == "kill":
        # SIGKILL over os._exit: nothing in this process may run another
        # instruction — no finally blocks, no daemon-thread flushes. This
        # is the crash the at-least-once contract is sworn against.
        os.kill(os.getpid(), signal.SIGKILL)
    raise CrashPointInjected(
        f"crash point {point!r} fired at arrival {armed.at}"
    )

"""RetryPolicy: classified retries with exponential backoff and full jitter.

The reference's entire failure story is "commit failures are survivable"
(/root/reference/src/kafka_dataset.py:131-135). That is the right call for
a *protocol* rejection — but a *transport* fault (broker unreachable,
request timeout) is a different animal: the operation is idempotent and
repeating it after a backoff is both safe and the only useful response.
This module is the one place that decision lives:

- **classification** — an exception is retryable iff it declares itself
  (``TpuKafkaError.retryable``, see errors.py) or its type is listed in
  ``retryable_errors``. Everything else propagates untouched on the first
  throw: a terminal error retried is a bug amplifier.
- **exponential backoff with full jitter** — attempt k sleeps
  ``uniform(0, min(max_delay, base * 2**k))``. Full jitter (not equal
  jitter, not decorrelated) because the failure mode that matters at
  fleet scale is the *thundering herd*: every consumer of a recovering
  broker retrying on the same schedule re-kills it. Uniform-from-zero
  spreads the retry storm across the whole window.
- **per-operation deadline** — ``deadline_s`` bounds the total time one
  operation may spend retrying, independent of ``max_attempts``; the
  budget check happens BEFORE sleeping, so the policy never burns a sleep
  it cannot follow with an attempt.
- **injectable time and randomness** — ``clock``/``sleep`` default to the
  real ones; tests inject ``ManualClock`` so every retry schedule is
  deterministic and instantaneous, and the jitter RNG is seeded so a
  failing schedule replays exactly.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

import numpy as np

from torchkafka_tpu.errors import BrokerUnavailableError


class ManualClock:
    """A clock/sleep pair for deterministic tests and benches: ``sleep``
    advances ``now`` instead of waiting, so a 30-second retry schedule
    runs in microseconds while every deadline comparison stays exact.
    Pass ``clock=mc.now, sleep=mc.sleep`` to a RetryPolicy (and
    ``clock=mc.now`` to a CircuitBreaker) and the whole resilience stack
    shares one synthetic timeline."""

    def __init__(self, start: float = 0.0) -> None:
        self._lock = threading.Lock()
        self._now = float(start)

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        with self._lock:
            self._now += max(0.0, float(seconds))

    # Explicit spelling for tests that advance time without "sleeping".
    advance = sleep


@dataclasses.dataclass
class RetryPolicy:
    """How one operation retries. Frozen decisions, injectable mechanics.

    ``max_attempts`` counts the total tries (first call included), so
    ``max_attempts=1`` means "never retry". ``deadline_s=None`` removes
    the wall-clock budget (attempts alone bound the loop)."""

    max_attempts: int = 6
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    deadline_s: float | None = 30.0
    retryable_errors: tuple[type[BaseException], ...] = (BrokerUnavailableError,)
    seed: int = 0
    clock: Callable[[], float] = time.monotonic
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0 or None, got {self.deadline_s}")
        # Seeded jitter + a lock: poll retries (stream producer thread) and
        # commit retries (the stream owner's thread) share this policy.
        self._rng = np.random.default_rng(self.seed)
        self._rng_lock = threading.Lock()

    # -------------------------------------------------------------- pieces

    def classify(self, exc: BaseException) -> bool:
        """True iff ``exc`` is retryable: listed in ``retryable_errors``
        or self-declared via the ``retryable`` attribute (errors.py's
        transport-independent classification)."""
        return isinstance(exc, self.retryable_errors) or bool(
            getattr(exc, "retryable", False)
        )

    def backoff_s(self, attempt: int) -> float:
        """Jittered delay AFTER failed attempt ``attempt`` (0-based):
        uniform over [0, min(max_delay, base * 2**attempt)]."""
        cap = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        if cap <= 0:
            return 0.0
        with self._rng_lock:
            return float(self._rng.uniform(0.0, cap))

    # --------------------------------------------------------------- runner

    def run(self, fn: Callable[[], object], *, on_retry=None):
        """Call ``fn`` under this policy. Terminal errors propagate from
        the first throw; retryable errors sleep-and-retry until attempts
        or deadline run out, then the LAST error propagates. ``on_retry``
        (attempt_index, exc, delay_s) observes each scheduled retry —
        metrics hooks, log lines, chaos bookkeeping."""
        start = self.clock()
        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as exc:  # noqa: BLE001 - classified below
                if not self.classify(exc):
                    raise
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                delay = self.backoff_s(attempt - 1)
                if (
                    self.deadline_s is not None
                    and (self.clock() - start) + delay >= self.deadline_s
                ):
                    raise
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                self.sleep(delay)

"""Transport-level resilience: retry/backoff, circuit breaking, poison
quarantine — the layer that turns injectable faults (source/chaos.py)
into survivable ones.

Composition order, outermost first::

    consumer = ResilientConsumer(          # retries + circuit breaker
        ChaosConsumer(                     # (tests) seeded fault injection
            MemoryConsumer(broker, ...),   # any Consumer transport
            seed=7, outage_rate=0.01,
        ),
        policy=RetryPolicy(...), breaker=CircuitBreaker(...),
    )

and ``PoisonQuarantine`` rides the processing layer above it
(``KafkaStream(on_processor_error="quarantine", quarantine=...)`` or
``StreamingGenerator(quarantine=...)``). Every piece takes injectable
clocks/seeds so chaos tests are deterministic and sleep-free
(``ManualClock``).
"""

from torchkafka_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from torchkafka_tpu.resilience.consumer import ResilientConsumer
from torchkafka_tpu.resilience.crashpoint import (
    REGISTERED_CRASH_POINTS,
    CrashPointInjected,
    arm,
    arm_from_env,
    crash_hook,
    disarm,
)
from torchkafka_tpu.resilience.policy import ManualClock, RetryPolicy
from torchkafka_tpu.resilience.quarantine import PoisonQuarantine
from torchkafka_tpu.utils.metrics import ResilienceMetrics

__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "REGISTERED_CRASH_POINTS",
    "CircuitBreaker",
    "CrashPointInjected",
    "ManualClock",
    "PoisonQuarantine",
    "ResilienceMetrics",
    "ResilientConsumer",
    "RetryPolicy",
    "arm",
    "arm_from_env",
    "crash_hook",
    "disarm",
]

"""PoisonQuarantine: a retry budget and a dead-letter exit per record.

Under at-least-once delivery a record whose *payload* crashes processing
is re-delivered forever — the infinite crash loop the reference has no
escape hatch for. The quarantine gives each ``(topic, partition, offset)``
a bounded processing-retry budget and, once it is spent, routes the
record to a dead-letter topic and declares it RESOLVED so the commit
watermark may advance past it.

The core invariant it preserves: **the committed watermark never covers
an unresolved record.** A record is resolved by exactly one of
(a) processing succeeded, (b) it was dropped by explicit policy, or
(c) its quarantine copy is DURABLE on the dead-letter topic. (c) is
enforced the same way serve.py enforces output durability: the DLQ
produce is sent AND acknowledged (``SendHandle.get``) before
``note_failure`` returns True — and a DLQ failure raises
``OutputDeliveryError``, the fail-stop = crash-before-commit discipline
from errors.py: better to re-deliver the poison record on restart than to
commit past a record that exists nowhere.

Callers (pipeline/stream.py's ``on_processor_error="quarantine"``,
serve.py's ``quarantine=``) hold the ledger; the quarantine only answers
"is this record resolved yet?":

    if quarantine.note_failure(record, exc):   # True => DLQ'd, durable
        ledger.dropped(record)                 # safe to retire the offset
    else:
        ...retry the record (budget remains)...

Budget semantics: ``budget`` counts FAILURES before dead-lettering, so
``budget=1`` dead-letters on the first failure and ``budget=3`` allows
two in-place retries (transient processing faults — a flaky external
tokenizer, an allocator hiccup) before declaring the record poison.
A processor that KNOWS the payload is bad raises ``PoisonRecordError``
(errors.py: terminal per record) and skips the remaining budget — the
retries exist for failures that might be transient, and that one, by
declaration, is not.
"""

from __future__ import annotations

import logging
import threading

from torchkafka_tpu.errors import OutputDeliveryError, PoisonRecordError
from torchkafka_tpu.source.producer import Producer
from torchkafka_tpu.source.records import Record
from torchkafka_tpu.utils.metrics import RateMeter

_logger = logging.getLogger(__name__)


class PoisonQuarantine:
    """Per-record failure budget + acknowledged dead-letter routing.

    ``producer``/``topic``: where quarantined records go (provenance,
    error, and attempt count ride in headers; the key is preserved so
    compacted/keyed DLQ topics keep working — same header convention as
    ``source.producer.dead_letter_to_topic``).
    ``budget``: failures per (topic, partition, offset) before the record
    is dead-lettered. ``timeout_s``: the DLQ durability wait.
    """

    def __init__(
        self,
        producer: Producer,
        topic: str,
        *,
        budget: int = 3,
        timeout_s: float | None = 30.0,
    ) -> None:
        if budget < 1:
            raise ValueError(f"budget must be >= 1 failure, got {budget}")
        self._producer = producer
        self._topic = topic
        self._budget = budget
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        # Failure counts for records still under budget. Entries are
        # removed on quarantine; successes never enter. Poison is rare by
        # definition, so this stays tiny — a pipeline where it does not is
        # already fail-stopping on the DLQ volume.
        self._counts: dict[tuple[str, int, int], int] = {}
        self.failures = RateMeter()  # every note_failure call
        self.quarantined = RateMeter()  # records dead-lettered (resolved)
        self.dlq_failures = RateMeter()  # DLQ produces that FAILED (each
        # one raised OutputDeliveryError — fail-stop — but the count
        # survives for the /metrics view of a broken DLQ)
        # The exact send kwargs of the most recent SUCCESSFUL dead-letter
        # produce — forensic/observability handle (what exactly went to
        # the DLQ, provenance headers included).
        self.last_dead_letter: dict | None = None

    @property
    def topic(self) -> str:
        return self._topic

    @property
    def producer(self):
        """The DLQ producer (read-only). serve.py's exactly_once mode
        validates the quarantine shares its transactional producer —
        the atomicity argument needs one transaction, not two brokers."""
        return self._producer

    def rebind_producer(self, producer) -> None:
        """Swap the DLQ delivery path. serve.py's exactly_once mode
        rebinds the quarantine onto its transactional outbox so the
        dead-letter copy is produced INSIDE the commit window's
        transaction — atomic with the offset that retires the poison
        record — rather than acknowledged ahead of it."""
        self._producer = producer

    def attempts(self, record: Record) -> int:
        """Failures recorded so far for this record (0 if unseen/resolved)."""
        with self._lock:
            return self._counts.get(
                (record.topic, record.partition, record.offset), 0
            )

    def note_failure(self, record: Record, exc: BaseException) -> bool:
        """Record one processing failure. Returns False while budget
        remains (the record is UNRESOLVED: retry it, or leave it pending
        so it re-delivers — never retire its offset). Returns True once
        the record has been dead-lettered AND the DLQ copy acknowledged
        durable — only then may the caller retire the offset. Raises
        ``OutputDeliveryError`` if the DLQ produce fails: fail-stop,
        because resolving the record without a durable copy would let the
        watermark commit past a record that then exists nowhere."""
        key = (record.topic, record.partition, record.offset)
        self.failures.add(1)
        with self._lock:
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            # A self-declared PoisonRecordError spends the whole budget:
            # terminal-per-record means a retry cannot end differently.
            if n < self._budget and not isinstance(exc, PoisonRecordError):
                return False
        self._dead_letter(record, exc, n)
        with self._lock:
            self._counts.pop(key, None)
        self.quarantined.add(1)
        _logger.warning(
            "poison record %s@%d:%d dead-lettered to %r after %d "
            "failure(s): %s",
            record.topic, record.partition, record.offset,
            self._topic, n, exc,
        )
        return True

    def _dead_letter(self, record: Record, exc: BaseException, attempts: int) -> None:
        kwargs = dict(
            topic=self._topic,
            value=record.value,
            key=record.key,
            headers=(
                ("dlq.error", str(exc).encode()),
                ("dlq.topic", record.topic.encode()),
                ("dlq.partition", str(record.partition).encode()),
                ("dlq.offset", str(record.offset).encode()),
                ("dlq.attempts", str(attempts).encode()),
            ),
        )
        try:
            self._producer.send(
                kwargs["topic"], kwargs["value"], key=kwargs["key"],
                headers=kwargs["headers"],
            ).get(self._timeout_s)
        except Exception as e:  # noqa: BLE001 - any DLQ failure fails stop
            self.dlq_failures.add(1)
            raise OutputDeliveryError(
                f"dead-letter produce to {self._topic!r} failed for "
                f"{record.topic}@{record.partition}:{record.offset}; "
                "refusing to resolve the record without a durable "
                "quarantine copy (crash-before-commit: it re-delivers)"
            ) from e
        self.last_dead_letter = kwargs

"""Commit tokens: the user-facing commit-after-step handle.

The reference's contract is "yield a batch → user processes it → commit the
offsets for exactly that batch" (/root/reference/src/auto_commit.py:55-58).
Its mechanism (a generator that commits *between* iterations, plus signals to
workers) cannot express "the step is an async device computation"; ours can:
each batch comes with a CommitToken, and ``token.commit(wait_for=loss)``
blocks on the device result, runs the pod barrier, then commits exactly that
batch's offsets.

Tokens are sequenced: commits may only move the offset watermark forward.
Committing token k after token k+n is a no-op (k's offsets are subsumed —
snapshots are monotonic per partition), which also makes double-commit
idempotent. Commit failure after a rebalance is logged and swallowed,
matching the reference's non-fatal contract
(/root/reference/src/kafka_dataset.py:131-135).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Mapping

from torchkafka_tpu.commit.barrier import CommitBarrier
from torchkafka_tpu.errors import CommitFailedError
from torchkafka_tpu.source.consumer import Consumer
from torchkafka_tpu.source.records import TopicPartition

logger = logging.getLogger(__name__)


class CommitSequencer:
    """Shared monotonic watermark across the tokens of one stream.

    Thread-safe: tokens are issued on the consuming thread while commits may
    execute on the stream's async-commit thread."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next_seq = 0
        self._high_water = -1

    def issue(self) -> int:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            return seq

    def superseded(self, seq: int) -> bool:
        with self._lock:
            return seq <= self._high_water

    def advance(self, seq: int) -> None:
        with self._lock:
            self._high_water = max(self._high_water, seq)


class CommitToken:
    """One batch's commit handle. Obtain via the stream; call once."""

    def __init__(
        self,
        consumer: Consumer,
        offsets: Mapping[TopicPartition, int],
        sequencer: CommitSequencer,
        barrier: CommitBarrier | None = None,
        on_commit: Callable[[float, bool], None] | None = None,
        executor: Callable[[], ThreadPoolExecutor] | None = None,
    ) -> None:
        self._consumer = consumer
        self._offsets = dict(offsets)
        self._sequencer = sequencer
        self._seq = sequencer.issue()
        self._barrier = barrier
        self._on_commit = on_commit
        self._executor = executor
        self._committed = False

    @property
    def offsets(self) -> dict[TopicPartition, int]:
        """Next-read offsets this token would commit (exactly this batch's
        records plus earlier drops — never carried-over records)."""
        return dict(self._offsets)

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def committed(self) -> bool:
        return self._committed

    def commit(self, wait_for: Any = None) -> bool:
        """Barrier, then commit this batch's offsets.

        ``wait_for``: any jax.Array/pytree produced by the step that consumed
        the batch; the commit happens only after it is device-complete on
        every host (pass None for host-only work).

        Returns True if offsets are durably committed (or were already covered
        by a later token), False if the commit failed non-fatally
        (re-delivery will occur). Raises BarrierError if the pod barrier
        failed — fail closed, nothing committed.
        """
        # The barrier runs on EVERY commit() call, before any fast path.
        # Ordering matters in SPMD: commit() call sites are identical across
        # hosts, but local outcomes (committed flag, sequencer watermark, a
        # host-local CommitFailedError) can diverge — if the barrier lived
        # behind those checks, hosts would make different numbers of
        # sync_global_devices calls and the pod would deadlock on mismatched
        # barrier names.
        if self._barrier is not None:
            self._barrier(wait_for)
        if self._committed:
            return True
        if self._sequencer.superseded(self._seq):
            # A later batch already committed; our offsets are subsumed.
            self._committed = True
            return True
        t0 = time.perf_counter()
        try:
            self._consumer.commit(self._offsets)
        except CommitFailedError as e:
            # Non-fatal by contract: the group rebalanced; records will be
            # re-delivered to the new partition owners.
            logger.error("offset commit failed (will re-deliver): %s", e)
            if self._on_commit is not None:
                self._on_commit(time.perf_counter() - t0, False)
            return False
        self._committed = True
        self._sequencer.advance(self._seq)
        logger.debug("committed batch seq=%d offsets=%s", self._seq, self._offsets)
        if self._on_commit is not None:
            self._on_commit(time.perf_counter() - t0, True)
        return True

    def commit_async(self, wait_for: Any = None) -> "Future[bool]":
        """Pipelined ``commit``: same barrier-then-commit, on the stream's
        single commit thread, so the training loop never stalls on the
        step-retirement wait (which can be ~100 ms of pure latency on
        remote/tunneled device transports). FIFO thread ⇒ commit order is
        preserved; semantics are unchanged — offsets still only commit
        after THIS batch's step provably retired. The returned Future
        resolves to commit()'s bool (or raises BarrierError); the stream's
        ``close()`` drains pending commits.
        """
        if self._executor is None:
            # Standalone token (no stream): degrade to a synchronous commit.
            fut: Future[bool] = Future()
            try:
                fut.set_result(self.commit(wait_for))
            except BaseException as e:  # noqa: BLE001 - delivered via future
                fut.set_exception(e)
            return fut
        return self._executor().submit(self.commit, wait_for)

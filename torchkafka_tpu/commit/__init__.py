"""Commit layer: ledger, barrier, tokens — the commit-after-step core."""

from torchkafka_tpu.commit.barrier import CommitBarrier, LocalBarrier
from torchkafka_tpu.commit.ledger import OffsetLedger
from torchkafka_tpu.commit.token import CommitSequencer, CommitToken

__all__ = [
    "CommitBarrier",
    "CommitSequencer",
    "CommitToken",
    "LocalBarrier",
    "OffsetLedger",
]

"""Offset ledger: commit-exactly-the-batch bookkeeping.

The reference commits "whatever was polled" — in the multiprocessing path the
committed offsets can even include records already fetched into the *next*
in-flight batch (SURVEY.md §3, CS-3 coarseness note). The TPU-native design
fixes this with explicit accounting (SURVEY.md §7, hard part (b)):

- ``fetched(r)``  — record r was polled off the broker (enters *pending*).
- ``dropped(r)``  — user transform returned None for r
  (/root/reference/src/kafka_dataset.py:161-162); r is done, it just never
  appears in a batch.
- ``emitted(r)``  — r is part of a batch handed to the consumer of the stream.

The committable watermark for a partition is the smallest offset still
pending — i.e. fetched but sitting in the carry-over buffer or an
un-emitted partial batch — or the fetch frontier if nothing is pending.
Committing a snapshot therefore never covers a record the user hasn't been
handed, no matter how records interleave with drops and batch boundaries.
"""

from __future__ import annotations

import threading

from torchkafka_tpu.source.records import Record, TopicPartition


class OffsetLedger:
    """Tracks per-partition fetch frontiers and pending (un-emitted) offsets.

    Thread-safe: the pipeline's fetch/transform thread mutates it while the
    consuming thread snapshots it at batch-emit time.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._frontier: dict[TopicPartition, int] = {}
        self._pending: dict[TopicPartition, set[int]] = {}

    def fetched(self, record: Record) -> None:
        with self._lock:
            tp = record.tp
            nxt = record.offset + 1
            if nxt > self._frontier.get(tp, 0):
                self._frontier[tp] = nxt
            self._pending.setdefault(tp, set()).add(record.offset)

    def dropped(self, record: Record) -> None:
        self._done(record)

    def emitted(self, record: Record) -> None:
        self._done(record)

    def _done(self, record: Record) -> None:
        with self._lock:
            pend = self._pending.get(record.tp)
            if pend is None or record.offset not in pend:
                # Tolerate: under at-least-once delivery a record can be
                # re-delivered after a rebalance while its first copy is still
                # in the batcher; both copies eventually resolve, the second
                # against an already-cleared offset. Raising here would turn a
                # legal re-delivery into a pipeline crash.
                return
            pend.remove(record.offset)

    def snapshot(self) -> dict[TopicPartition, int]:
        """Committable next-read offsets right now.

        For each partition: min(pending) if any record is still in flight,
        else the fetch frontier. Calling this immediately after marking a
        batch ``emitted`` yields offsets covering exactly that batch plus any
        earlier drops — and never a carried-over record.
        """
        with self._lock:
            out: dict[TopicPartition, int] = {}
            for tp, frontier in self._frontier.items():
                pend = self._pending.get(tp)
                out[tp] = min(pend) if pend else frontier
            return out

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(p) for p in self._pending.values())

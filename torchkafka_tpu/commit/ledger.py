"""Offset ledger: commit-exactly-the-batch bookkeeping.

The reference commits "whatever was polled" — in the multiprocessing path the
committed offsets can even include records already fetched into the *next*
in-flight batch (SURVEY.md §3, CS-3 coarseness note). The TPU-native design
fixes this with explicit accounting (SURVEY.md §7, hard part (b)):

- ``fetched(r)``  — record r was polled off the broker (enters *pending*).
- ``dropped(r)``  — user transform returned None for r
  (/root/reference/src/kafka_dataset.py:161-162); r is done, it just never
  appears in a batch.
- ``emitted(r)``  — r is part of a batch handed to the consumer of the stream.

The committable watermark for a partition is the smallest offset still
pending — i.e. fetched but sitting in the carry-over buffer or an
un-emitted partial batch — or the fetch frontier if nothing is pending.
Committing a snapshot therefore never covers a record the user hasn't been
handed, no matter how records interleave with drops and batch boundaries.

Representation: per partition, pending is the interval [low, frontier) minus
a (normally empty) set of out-of-order completions. Kafka partitions are
ordered logs, so fetches arrive offset-ascending and completions almost
always retire ``low`` itself — a couple of integer ops per record, no
per-record set churn. The set only fills on genuinely out-of-order completion
(e.g. interleaved re-delivery), and drains as ``low`` catches up.
"""

from __future__ import annotations

import threading

import numpy as np

from torchkafka_tpu.source.records import Record, TopicPartition


class _Partition:
    __slots__ = ("low", "frontier", "ooo")

    def __init__(self, first_offset: int) -> None:
        self.low = first_offset  # smallest possibly-pending offset
        self.frontier = first_offset  # next-fetch position (exclusive)
        self.ooo: set[int] = set()  # done out-of-order, all in (low, frontier)

    def _skip_gap(self, start: int) -> None:
        """Offsets [frontier, start) will never be delivered (log compaction,
        transaction markers): they must not count as pending."""
        if start > self.frontier:
            if self.low == self.frontier:
                self.low = start
            else:
                self.ooo.update(range(self.frontier, start))

    def fetch(self, offset: int) -> None:
        if offset < self.low:
            # Re-delivery below the done watermark (consumer seeked back):
            # that range is pending again.
            self.low = offset
        else:
            self._skip_gap(offset)
        nxt = offset + 1
        if nxt > self.frontier:
            self.frontier = nxt

    def fetch_span(self, start: int, count: int) -> None:
        """O(1) bulk fetch of the contiguous offsets [start, start+count)."""
        if start < self.low:
            self.low = start
        else:
            self._skip_gap(start)
        if start + count > self.frontier:
            self.frontier = start + count

    def done_run(self, first: int, last: int) -> bool:
        """O(1) bulk done of the contiguous offsets [first, last]; True if
        the fast path applied (run starts exactly at the watermark)."""
        if first == self.low:
            self.low = last + 1
            ooo = self.ooo
            while ooo and self.low in ooo:
                ooo.remove(self.low)
                self.low += 1
            return True
        return False

    def done(self, offset: int) -> None:
        if offset == self.low:
            self.low += 1
            ooo = self.ooo
            while ooo and self.low in ooo:
                ooo.remove(self.low)
                self.low += 1
        elif offset > self.low:
            if offset < self.frontier:
                self.ooo.add(offset)
        # offset < low: already done (re-delivered duplicate) — tolerated,
        # see the at-least-once note in OffsetLedger._done.

    @property
    def committable(self) -> int:
        return self.low  # == frontier when nothing is pending

    @property
    def pending(self) -> int:
        return (self.frontier - self.low) - len(self.ooo)


class OffsetLedger:
    """Tracks per-partition fetch frontiers and pending (un-emitted) offsets.

    Thread-safe: the pipeline's fetch/transform thread mutates it while the
    consuming thread snapshots it at batch-emit time.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._parts: dict[TopicPartition, _Partition] = {}

    def _part(self, tp: TopicPartition, offset: int) -> _Partition:
        part = self._parts.get(tp)
        if part is None:
            part = self._parts[tp] = _Partition(offset)
        return part

    def fetched(self, record: Record) -> None:
        with self._lock:
            self._part(record.tp, record.offset).fetch(record.offset)

    def fetched_many(self, records: list[Record]) -> None:
        """Bulk ``fetched``: one lock acquisition per poll chunk."""
        with self._lock:
            for record in records:
                self._part(record.tp, record.offset).fetch(record.offset)

    def dropped(self, record: Record) -> None:
        self._done(record)

    def emitted(self, record: Record) -> None:
        self._done(record)

    def _done(self, record: Record) -> None:
        # Unknown partitions are tolerated: under at-least-once delivery a
        # record can be re-delivered after a rebalance while its first copy
        # is still in the batcher; both copies eventually resolve, the second
        # as a no-op. Raising would turn a legal re-delivery into a crash.
        with self._lock:
            part = self._parts.get(record.tp)
            if part is not None:
                part.done(record.offset)

    # ------------------------------------------------------- vectorized path

    def fetched_spans(self, spans: list[tuple[TopicPartition, int, int]]) -> None:
        """O(spans) bulk fetch: each span is (tp, start_offset, count) of
        contiguous offsets, as produced by one partition's poll run. The
        per-record cost of ``fetched_many`` (a dict hit and int compares per
        record — the dominant ledger cost at millions of records/sec)
        collapses to one call per partition run."""
        with self._lock:
            for tp, start, count in spans:
                self._part(tp, start).fetch_span(start, count)

    def done_array(self, tp: TopicPartition, offsets: np.ndarray) -> None:
        """Bulk done of a sorted-ascending, unique offset array for one
        partition. Contiguous runs starting at the watermark — the shape
        every in-order batch emit produces — retire in O(1); anything else
        falls back to per-offset handling (re-delivery interleavings)."""
        n = int(offsets.shape[0])
        if n == 0:
            return
        first = int(offsets[0])
        last = int(offsets[-1])
        with self._lock:
            part = self._parts.get(tp)
            if part is None:
                return
            if last - first == n - 1 and part.done_run(first, last):
                return
            for off in offsets.tolist():
                part.done(int(off))

    def drop(self, tps) -> None:
        """Forget every tracked offset of the given partitions — the
        REVOCATION reset. A rebalance that takes a partition away leaves
        its fetched-but-unretired records stranded here (their queued
        copies were pruned; the new owner serves them); if the partition
        later RETURNS, those stale pending entries would hold the
        snapshot below the broker's committed watermark and the next
        commit would REGRESS it (last-write-wins, like Kafka). Dropping
        on revocation makes a comeback start from the fresh fetch
        position; completions of already-in-flight work for a dropped
        partition resolve as tolerated no-ops (see ``_done``)."""
        with self._lock:
            for tp in tps:
                self._parts.pop(tp, None)

    def snapshot(self) -> dict[TopicPartition, int]:
        """Committable next-read offsets right now.

        For each partition: the smallest still-pending offset if any record
        is in flight, else the fetch frontier. Calling this immediately after
        marking a batch ``emitted`` yields offsets covering exactly that
        batch plus any earlier drops — and never a carried-over record.
        """
        with self._lock:
            return {tp: part.committable for tp, part in self._parts.items()}

    def pending_count(self) -> int:
        with self._lock:
            return sum(part.pending for part in self._parts.values())

    def pending_by_partition(self) -> dict[TopicPartition, int]:
        """Per-partition in-flight (fetched-but-unretired) record counts —
        the fleet watermark view's 'how far behind is each replica'."""
        with self._lock:
            return {tp: part.pending for tp, part in self._parts.items()}


def merged_watermarks(
    snapshots: "list[dict[TopicPartition, int]]",
) -> dict[TopicPartition, int]:
    """Fleet-level committable view over several replicas' ledgers.

    Under the consumer-group invariant each partition is owned by exactly
    one member, so the merged view is normally a disjoint union. During a
    handoff window (rebalance mid-redelivery) two ledgers can briefly know
    the same partition; the merge takes the MINIMUM — a watermark that
    never covers another replica's still-pending records, the same
    fail-low rule the per-replica snapshot applies within a partition."""
    out: dict[TopicPartition, int] = {}
    for snap in snapshots:
        for tp, off in snap.items():
            out[tp] = min(out[tp], off) if tp in out else off
    return out

"""Pod-wide commit barrier.

Replaces the reference's POSIX-signal control plane (SIGUSR1 "commit now"
from orchestrator to worker, /root/reference/src/kafka_dataset.py:47-55,235-239;
/root/reference/src/auto_commit.py:59-72) with a first-class barrier:

1. wait for the step's device work to retire locally (jax.block_until_ready),
2. synchronize every process in the pod over ICI/DCN
   (multihost_utils.sync_global_devices),
3. only then is the commit allowed to proceed.

Fail-closed: if any host dies, the barrier raises on the survivors instead of
timing out silently; no host commits, Kafka re-delivers the batch — the zero
uncommitted-batch-loss property (SURVEY.md §7 hard part (c)). The signal-race
class the reference handles with its deferred-flag dance (SURVEY.md §5 race
row) does not exist here: commits run synchronously on the host's own thread,
never from an interrupt context.
"""

from __future__ import annotations

import logging
from typing import Any

import jax

from torchkafka_tpu.errors import BarrierError

logger = logging.getLogger(__name__)


class CommitBarrier:
    """Callable barrier used by CommitToken before offsets are committed.

    Single-process (the degenerate case, SURVEY.md §7 minimum slice): only
    ``block_until_ready``. Multi-process: adds a pod-wide
    ``sync_global_devices`` with a per-call unique name so distinct batches
    can never alias each other's barrier.
    """

    def __init__(self, name: str = "tpukafka_commit", strict: bool = True) -> None:
        self._name = name
        self._calls = 0
        self._strict = strict

    @staticmethod
    def _retire(wait_for: Any) -> None:
        """Prove the step's device work is complete.

        ``block_until_ready`` plus — in strict mode — a one-scalar host
        fetch from the first array leaf. The fetch exists because
        experimental/tunneled backends (e.g. the axon TPU proxy) have been
        observed returning from block_until_ready before the computation
        retires; committing offsets on that lie would break the
        at-least-once contract, so the barrier pessimistically demands a
        value. Cost: one scalar D2H per batch.
        """
        jax.block_until_ready(wait_for)
        leaves = [
            leaf for leaf in jax.tree_util.tree_leaves(wait_for)
            if isinstance(leaf, jax.Array) and leaf.size > 0
        ]
        if leaves:
            jax.device_get(leaves[0].ravel()[0])

    def __call__(self, wait_for: Any = None) -> None:
        try:
            if wait_for is not None:
                # Retire the step that consumed the batch: host-side proof the
                # batch's results exist before its offsets become committable
                # (the reference's yield-then-commit ordering,
                # /root/reference/src/auto_commit.py:55-58, made device-aware).
                if self._strict:
                    self._retire(wait_for)
                else:
                    jax.block_until_ready(wait_for)
            self._calls += 1
            # Executed for real in tests/test_pod.py (spawned jax.distributed
            # processes) — the cross-process commit coordination path.
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"{self._name}:{self._calls}")
        except BarrierError:
            raise
        except Exception as e:
            # Fail closed: a barrier failure means we cannot prove every host
            # finished the step -> nobody commits -> Kafka re-delivers.
            raise BarrierError(f"commit barrier failed (no offsets committed): {e}") from e


#: Barrier that only waits for local device work — explicit single-host mode.
class LocalBarrier(CommitBarrier):
    def __call__(self, wait_for: Any = None) -> None:
        if wait_for is not None:
            if self._strict:
                self._retire(wait_for)
            else:
                jax.block_until_ready(wait_for)

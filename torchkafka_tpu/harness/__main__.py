"""CLI: python -m torchkafka_tpu.harness --scenario 3 --size tiny"""

from __future__ import annotations

import argparse
import json

from torchkafka_tpu.harness.scenarios import SCENARIOS, run_scenario


def main() -> None:
    ap = argparse.ArgumentParser(description="torchkafka_tpu benchmark harness")
    ap.add_argument("--scenario", type=int, choices=sorted(SCENARIOS), default=None,
                    help="which BASELINE scenario; default: all")
    ap.add_argument("--size", choices=("tiny", "full"), default="tiny")
    ap.add_argument("--model-scale", choices=("45m", "1b", "8b"), default=None,
                    help="serving scenarios (5/7) only: serve the zoo model "
                    "at this scale (8b = int8) with HBM roofline accounting")
    ap.add_argument("--serve-eos", action="store_true",
                    help="scenario 7 at a model scale: EOS ON with 8-tick "
                    "blocks — the continuous-batching row (slots readmit "
                    "mid-stream); default at scale is EOS off, one dispatch "
                    "per generation (the throughput ceiling)")
    ap.add_argument("--quantized", action="store_true", default=None,
                    help="serve the zoo scale weight-only int8 (default: "
                    "only 8b; decode is bytes-bound, so int8 halves the "
                    "streamed bytes vs bf16)")
    ap.add_argument("--kv-int8", action="store_true",
                    help="scenario 7: int8 slot pool — ~52%% of bf16 "
                    "pool bytes, serves slot/context budgets bf16 "
                    "cannot fit, and with scatter writes equal-slot "
                    "throughput is neutral-to-better than bf16 KV "
                    "(see PERF.md)")
    ap.add_argument("--kv-kernel", choices=("auto", "on", "off"),
                    default="auto",
                    help="scenario 7 with --kv-int8: the Pallas dynamic-length "
                    "decode-attention kernel for the pool read (auto = on "
                    "when honorable; on = require, raise otherwise; off = "
                    "XLA scale-folded read — the paired control)")
    ap.add_argument("--spec", action="store_true",
                    help="scenario 7: speculative continuous-batching "
                    "serving (SpecStreamingGenerator) — the layer-truncated "
                    "self-draft proposes k tokens per slot, one multi-query "
                    "verify advances each slot by its accepted length; "
                    "token-exact vs the plain path, reports MEASURED "
                    "acceptance")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="--spec: draft tokens proposed per verify round")
    ap.add_argument("--spec-draft-layers", type=int, default=None,
                    help="--spec: layers in the truncated self-draft "
                    "(default: half the target's)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="scenario 7: sampled serving (0 = greedy)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="scenario 7 with --temperature: per-step top-k "
                    "filter (static-shape; models.generate.sample_logits)")
    ap.add_argument("--top-p", type=float, default=None,
                    help="scenario 7 with --temperature: nucleus mass in "
                    "(0, 1] — minimal prefix reaching p stays sampleable")
    ap.add_argument("--replicas", type=int, default=2,
                    help="scenarios 10-13/15-19 (serving fleet / "
                    "chaos soak / prefix-cache fleet / warm failover / SLO "
                    "observability / traffic observatory / process-fleet "
                    "kill storm / exactly-once kill storm): replica count")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="scenario 14 (chunked-prefill storm): suffix "
                    "tokens the fused tick carries alongside decode "
                    "(default: one block) — smaller bounds per-tick "
                    "prefill work, the decode-latency lever")
    args = ap.parse_args()
    if args.scenario:
        nums = [args.scenario]
    elif args.model_scale:
        nums = [5, 7]  # the scenarios the flag applies to
    else:
        nums = sorted(SCENARIOS)
    for n in nums:
        print(json.dumps(run_scenario(
            n, args.size, model_scale=args.model_scale,
            serve_eos=args.serve_eos, quantized=args.quantized,
            kv_int8=args.kv_int8,
            kv_kernel={"auto": "auto", "on": True, "off": False}[args.kv_kernel],
            spec=args.spec, spec_k=args.spec_k,
            spec_draft_layers=args.spec_draft_layers,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            replicas=args.replicas, prefill_chunk=args.prefill_chunk,
        )))


if __name__ == "__main__":
    main()

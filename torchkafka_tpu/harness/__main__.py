"""CLI: python -m torchkafka_tpu.harness --scenario 3 --size tiny"""

from __future__ import annotations

import argparse
import json

from torchkafka_tpu.harness.scenarios import SCENARIOS, run_scenario


def main() -> None:
    ap = argparse.ArgumentParser(description="torchkafka_tpu benchmark harness")
    ap.add_argument("--scenario", type=int, choices=sorted(SCENARIOS), default=None,
                    help="which BASELINE scenario; default: all")
    ap.add_argument("--size", choices=("tiny", "full"), default="tiny")
    args = ap.parse_args()
    nums = [args.scenario] if args.scenario else sorted(SCENARIOS)
    for n in nums:
        print(json.dumps(run_scenario(n, args.size)))


if __name__ == "__main__":
    main()

"""Benchmark harness: the five BASELINE.md scenarios.

The reference publishes no benchmarks (SURVEY.md §6) — this harness defines
the measured surface: records/sec sustained ingest and offset-commit latency
percentiles for each BASELINE.json config, sized down to run anywhere
(``size='tiny'`` on the CPU mesh) or at full scale on real hardware
(``size='full'``).

Run: ``python -m torchkafka_tpu.harness --scenario 1..5 [--size tiny|full]``.
"""

from torchkafka_tpu.harness.scenarios import SCENARIOS, run_scenario

__all__ = ["SCENARIOS", "run_scenario"]

"""The harness scenarios (BASELINE.json's five configs + net-new ones),
each returning a metrics dict.

| # | Scenario | Reference analog |
|---|----------|------------------|
| 1 | single-process float records, batch 4, 1 partition | README MyDataset flow (/root/reference/README.md:86-102) |
| 2 | JSON → tokenized int32, 8 partitions, threaded transform | README multiproc flow (/root/reference/README.md:104-132) |
| 3 | mesh-sharded global batch, transformer train, commit-after-step | none (new capability) |
| 4 | image bytes → on-device decode/resize → ResNet-50 inference | none |
| 5 | prompt topic → KV-cache generate → commit post-generation | none |
| 6 | scenario 1 at batch 256 | isolates the reference's toy batch-4 choice |
| 7 | continuous-batching serving (slot recycling, EOS) | none |
| 8 | streaming CTR: DLRM train, tp-sharded embedding tables | none |
| 9 | ragged text → length-bucketed batches → per-width train steps | none |
| 10 | serving fleet: QoS admission + graceful drain | none |
| 11 | chaos soak: broker outage + poison prompt → recovery + DLQ | none |
| 12 | prefix-cache fleet: per-tenant system prompts, paged KV reuse | none |
| 13 | warm failover: seeded replica kill + journal resume | none |
| 14 | chunked-prefill prompt storm (bounded decode latency) | none |
| 15 | traced fleet: per-tenant SLOs + Prometheus endpoint | none |
| 16 | Zipf burst storm: windowed SLOs + burn-rate shedding | none |
| 17 | real-process fleet: SIGKILL mid-storm, zombie fencing | none |
| 18 | exactly-once output: transactional SIGKILL storm | none |
| 19 | durable broker: uncleanly killed + WAL-recovered mid-storm | none |
| 20 | sharded paged serving: paged+int8+kernel-probe on a {data,tp} mesh | none |

Every scenario runs the full transactional loop (poll → transform → batch →
device → step → barrier → commit) and reports ``records_per_s`` plus commit
latency percentiles from the stream's own metrics.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable

import numpy as np

_SIZES = ("tiny", "full")


def _result(name: str, rows: int, elapsed: float, stream, extra: dict | None = None) -> dict:
    out = {
        **stream.metrics.summary(),
        "scenario": name,
        "records": rows,
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(rows / elapsed, 1) if elapsed > 0 else None,
    }
    if extra:
        out.update(extra)
    return out


def _drain(
    stream, step: Callable[[Any], Any] | None, total: int,
    sync_commit: bool = False,
) -> tuple[int, float]:
    """Run the transactional loop until ``total`` rows are consumed; the
    last commit is durable inside the timed region. ``sync_commit`` commits
    inline instead of through the FIFO commit thread — pair it with a
    ``prefetch=0`` stream for latency-shaped loops (sub-ms batches), where
    a per-batch executor handoff costs more than the commit itself."""
    rows = 0
    fut = None
    t0 = time.perf_counter()
    for batch, token in stream:
        wait = step(batch) if step is not None else None
        if sync_commit:
            token.commit(wait_for=wait)
        else:
            fut = token.commit_async(wait_for=wait)
        rows += batch.valid_count
        if rows >= total:
            break
    if fut is not None:
        fut.result(timeout=600)
    return rows, time.perf_counter() - t0


_PAIR_GROUP_SEQ = iter(range(10**9))


def _paired_host_ratio(
    broker, topic: str, n_parts: int, ours_slice, ref_process, batch_size: int,
    n_slice: int, slices: int = 2,
) -> dict:
    """Alternating ours/reference-pattern slices over the SAME broker
    records — bench.py's pairing discipline brought to the harness
    (VERDICT r3 item 6): host-bound absolute numbers swing up to 15× with
    box contention across rounds, but adjacent slices sample the same
    conditions, so the per-pair ratio is the stable signal. Reports the
    median of per-pair ratios plus both sides' rates.

    ``ours_slice(group_id, n) -> (rows, elapsed)`` runs the framework path;
    ``ref_process(record) -> torch tensor/pytree`` defines the reference
    analog, executed through the REAL compat stack (KafkaDataset subclass →
    DataLoader → auto_commit, /root/reference/README.md:86-102) with
    commit-per-batch, the reference's own cadence."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.compat import KafkaDataset, auto_commit

    def ref_slice(group_id: str, n: int) -> tuple[int, float]:
        from torch.utils.data import DataLoader

        class RefDataset(KafkaDataset):
            def _process(self, record):
                return ref_process(record)

            @classmethod
            def new_consumer(cls, *args, **kwargs):
                kwargs.pop("_is_placeholder", None)
                return tk.MemoryConsumer(
                    broker, *args,
                    assignment=tk.partitions_for_process(topic, n_parts, 0, 1),
                    consumer_timeout_ms=500, **kwargs,
                )

        dataset = RefDataset(topic, group_id=group_id)
        loader = DataLoader(dataset, batch_size=batch_size)
        rows = 0
        t0 = _time.perf_counter()
        for batch in auto_commit(loader):
            first = batch[0] if isinstance(batch, (list, tuple)) else batch
            rows += int(first.shape[0])
            if rows >= n:
                break
        elapsed = _time.perf_counter() - t0
        dataset.close()
        return rows, elapsed

    ratios, ours_rates, ref_rates = [], [], []
    for _ in range(slices):
        o_rows, o_t = ours_slice(f"pair-ours-{next(_PAIR_GROUP_SEQ)}", n_slice)
        r_rows, r_t = ref_slice(f"pair-ref-{next(_PAIR_GROUP_SEQ)}", n_slice)
        ours_rates.append(o_rows / o_t)
        ref_rates.append(r_rows / r_t)
        ratios.append(ours_rates[-1] / ref_rates[-1])
    return {
        "vs_reference_pattern": round(float(np.median(ratios)), 3),
        "pair_ratios": [round(r, 3) for r in ratios],
        "ours_rows_per_s": round(float(np.median(ours_rates)), 1),
        "reference_pattern_rows_per_s": round(float(np.median(ref_rates)), 1),
    }


def scenario_1(size: str = "tiny", batch_size: int = 4, name: str = "1:single-process") -> dict:
    """Single-process, 1 partition, batch 4: the reference's README flow —
    each record becomes a float32[8] row (torch.rand(8) analog,
    /root/reference/README.md:40-44). Batch 4 is faithful to the reference's
    example (README.md:84,97) and is iteration-bound by design; scenario 6
    reruns this flow at batch 256 so the comparison is not an artifact of
    the reference's toy batch size. Host-bound, so the headline is the
    PAIRED ratio (see ``_paired_host_ratio``), not the weather-dependent
    absolute rate."""
    import torch

    import torchkafka_tpu as tk

    n = 512 if size == "tiny" else 200_000
    broker = tk.InMemoryBroker()
    broker.create_topic("t1", partitions=1)
    rng = np.random.default_rng(0)
    broker.produce_many("t1", (rng.random(8).astype(np.float32).tobytes() for _ in range(n)))
    consumer = tk.MemoryConsumer(
        broker, "t1", group_id="s1", assignment=[tk.TopicPartition("t1", 0)]
    )
    # Batch 4 is latency-shaped: a per-batch thread handoff + commit-thread
    # submit cost more than the 4-row batch itself, so small batches take
    # the stream's documented synchronous mode (prefetch=0, inline commit)
    # — symmetric with the reference pattern, which is also single-threaded.
    # Large batches (scenario 6) keep the pipelined mode.
    latency_shaped = batch_size < 64
    stream_kw = dict(
        to_device=False, idle_timeout_ms=1000, owns_consumer=True,
        prefetch=0 if latency_shaped else 2,
    )
    with tk.KafkaStream(
        consumer, tk.fixed_width(8, np.float32), batch_size=batch_size,
        # Host-only, like the reference it mirrors (its DataLoader yields CPU
        # torch tensors); shipping batch-of-4 arrays to an accelerator per
        # iteration would benchmark the transport, not the loop.
        **stream_kw,
    ) as stream:
        rows, elapsed = _drain(
            stream, None, n // batch_size * batch_size,
            sync_commit=latency_shaped,
        )

    def ours_slice(group_id: str, n_s: int):
        c = tk.MemoryConsumer(
            broker, "t1", group_id=group_id,
            assignment=[tk.TopicPartition("t1", 0)],
        )
        with tk.KafkaStream(
            c, tk.fixed_width(8, np.float32), batch_size=batch_size,
            **stream_kw,
        ) as s:
            return _drain(s, None, n_s, sync_commit=latency_shaped)

    paired = _paired_host_ratio(
        broker, "t1", 1, ours_slice,
        lambda rec: torch.from_numpy(
            np.frombuffer(rec.value, dtype=np.float32).copy()
        ),
        batch_size, (n // 2) // batch_size * batch_size,
    )
    return _result(name, rows, elapsed, stream, {"batch_size": batch_size, **paired})


def scenario_6(size: str = "tiny") -> dict:
    """Scenario 1 at a realistic batch size (256): same records, same
    host-only loop — isolates how much of scenario 1's number is the
    reference's example batch of 4."""
    return scenario_1(size, batch_size=256, name="6:single-process-b256")


def scenario_2(size: str = "tiny") -> dict:
    """JSON records → tokenized int32[seq], 8 partitions, chunked transform
    (the multiproc DataLoader analog — thread/chunk parallel instead of
    process parallel). Host-bound: paired against the torch-user analog
    (json.loads + per-record tokenize in ``_process``), host-only on both
    sides so the pair isolates the transform architecture."""
    import torch

    import torchkafka_tpu as tk

    n, seq = (2048, 32) if size == "tiny" else (500_000, 128)
    broker = tk.InMemoryBroker()
    broker.create_topic("t2", partitions=8)
    rng = np.random.default_rng(0)
    words = ["stream", "kafka", "tpu", "offset", "commit", "batch", "mesh"]
    broker.produce_many(
        "t2",
        (
            json.dumps({"text": " ".join(rng.choice(words, 6))}).encode()
            for _ in range(n)
        ),
    )
    consumer = tk.MemoryConsumer(
        broker, "t2", group_id="s2",
        assignment=tk.partitions_for_process("t2", 8, 0, 1),
    )
    with tk.KafkaStream(
        consumer, tk.json_tokens("text", seq), batch_size=256,
        to_device=True, idle_timeout_ms=1000, owns_consumer=True,
    ) as stream:
        rows, elapsed = _drain(stream, None, n // 256 * 256)

    def ours_slice(group_id: str, n_s: int):
        c = tk.MemoryConsumer(
            broker, "t2", group_id=group_id,
            assignment=tk.partitions_for_process("t2", 8, 0, 1),
        )
        with tk.KafkaStream(
            c, tk.json_tokens("text", seq), batch_size=256,
            to_device=False, idle_timeout_ms=1000, owns_consumer=True,
        ) as s:
            return _drain(s, None, n_s)

    def ref_process(rec):
        text = json.loads(rec.value)["text"].encode()
        row = np.full((seq,), 0, np.int32)
        take = min(len(text), seq)
        row[:take] = np.frombuffer(text[:take], np.uint8)
        return torch.from_numpy(row)

    paired = _paired_host_ratio(
        broker, "t2", 8, ours_slice, ref_process, 256,
        (n // 2) // 256 * 256,
    )
    return _result("2:json-tokenize", rows, elapsed, stream, paired)


def scenario_3(size: str = "tiny") -> dict:
    """Mesh-sharded global batches training the flagship transformer with
    commit-after-step — the heart of the TPU-native design (BASELINE
    north star; no reference analog)."""
    import jax
    import jax.numpy as jnp
    import optax

    import torchkafka_tpu as tk
    from torchkafka_tpu.models import TransformerConfig, make_train_step

    n_dev = len(jax.devices())
    mesh = tk.make_mesh({"data": n_dev})
    seq = 64 if size == "tiny" else 512
    cfg = (
        TransformerConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=128, max_seq_len=seq, dtype=jnp.float32)
        if size == "tiny"
        else TransformerConfig(max_seq_len=seq)
    )
    steps = 8 if size == "tiny" else 50
    local_batch = 2 * n_dev if size == "tiny" else 8 * n_dev
    n = steps * local_batch

    broker = tk.InMemoryBroker()
    parts = max(n_dev, 4)
    broker.create_topic("t3", partitions=parts)
    rng = np.random.default_rng(0)
    broker.produce_many(
        "t3",
        (rng.integers(0, cfg.vocab_size, seq, dtype=np.int32).tobytes() for _ in range(n)),
    )
    consumer = tk.MemoryConsumer(
        broker, "t3", group_id="s3",
        assignment=tk.partitions_for_process("t3", parts, 0, 1),
    )
    init_fn, step_fn = make_train_step(cfg, mesh, optax.adamw(1e-3))
    params, opt_state = init_fn(jax.random.key(0))
    state = {"params": params, "opt": opt_state, "losses": []}

    def step(batch):
        mask = (np.arange(batch.batch_size) < batch.valid_count).astype(np.int32)
        mask = jnp.broadcast_to(jnp.asarray(mask)[:, None], batch.data.shape)
        state["params"], state["opt"], loss = step_fn(
            state["params"], state["opt"], batch.data, mask
        )
        state["losses"].append(loss)
        return loss

    with tk.KafkaStream(
        consumer, tk.fixed_width(seq, np.int32), batch_size=local_batch,
        mesh=mesh, idle_timeout_ms=2000, owns_consumer=True,
    ) as stream:
        rows, elapsed = _drain(stream, step, n)
    losses = [float(x) for x in state["losses"]]
    extra = {"mesh": dict(mesh.shape), "first_loss": round(losses[0], 4),
             "last_loss": round(losses[-1], 4)}
    extra.update(_train_mfu(cfg, state, step_fn, local_batch, seq, n_dev))
    return _result("3:mesh-train", rows, elapsed, stream, extra)


def _train_mfu(cfg, state, step_fn, batch: int, seq: int, n_dev: int) -> dict:
    """Pure train-step time (ingest excluded) and an MFU estimate.

    FLOPs/step ≈ 6·N_params·tokens (fwd+bwd matmul rule of thumb)
    + 6·L·d_model·B·S² (causal attention, fwd+bwd); peak = 197 TFLOP/s
    bf16 per v5e chip × the mesh's device count. Timed with
    ``utils.timing.device_step_seconds`` — the step chained inside ONE
    jitted fori_loop, sloped over two loop lengths. On RPC-dispatch
    transports a Python-loop chain of jitted calls measures the HOST's
    dispatch rate (~10 ms/call here), not the device: wall/step keeps
    falling as the window grows and never converges."""
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import count_params
    from torchkafka_tpu.utils.timing import device_step_seconds

    if jax.default_backend() != "tpu":
        return {}
    n_params = count_params(state["params"])
    tokens = jnp.zeros((batch, seq), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.int32)
    step_s, slope_ok = device_step_seconds(
        step_fn, state["params"], state["opt"], tokens, mask
    )
    if not slope_ok:
        return {"params_m": round(n_params / 1e6, 1), "slope_ok": False}
    flops = 6 * n_params * batch * seq + 6 * cfg.n_layers * cfg.d_model * batch * seq**2
    mfu = flops / step_s / (197e12 * n_dev)
    return {
        "params_m": round(n_params / 1e6, 1),
        "step_ms": round(step_s * 1e3, 2),
        "flops_per_step_g": round(flops / 1e9, 1),
        "mfu_pct": round(mfu * 100, 2),
        "slope_ok": True,
    }


def scenario_4(size: str = "tiny") -> dict:
    """PNG topic → host C++ decode (zlib inflate + defilter) → on-device
    resize → ResNet-50 inference, commit per batch (BASELINE config 4; no
    reference analog — but the host decompression is exactly the per-record
    CPU work the reference's ``_process`` hook exists for,
    /root/reference/src/kafka_dataset.py:173-186). VERDICT r2: a reshape is
    not a decode; this measures through a real compressed-image path and
    reports the host-decode vs device-infer split."""
    import time as _time

    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk
    from torchkafka_tpu.models import resnet
    from torchkafka_tpu.transform.image import encode_png_rgb

    h = w = 64
    out_size = 64 if size == "tiny" else 224
    n, batch = (64, 8) if size == "tiny" else (8192, 64)
    broker = tk.InMemoryBroker()
    broker.create_topic("t4", partitions=4)
    rng = np.random.default_rng(0)
    # Smooth sinusoid field + low noise: compresses ~1.8x under Paeth —
    # photo-like, not white noise (incompressible at 1.0x) — so inflate and
    # defiltering do real work per record. Paeth is both the realistic
    # adaptive-encoder choice and the most expensive filter to reverse.
    yy, xx = np.mgrid[0:h, 0:w]
    base = (96 + 80 * np.sin(xx / 9.0) + 60 * np.cos(yy / 7.0))[:, :, None] + (
        np.array([0, 20, 40])
    )
    payloads = [
        encode_png_rgb(
            np.clip(base + rng.integers(0, 4, (h, w, 3)), 0, 255).astype(
                np.uint8
            ),
            filters=4,
        )
        for _ in range(min(n, 256))
    ]
    png_bytes = float(np.mean([len(p) for p in payloads]))
    broker.produce_many("t4", (payloads[i % len(payloads)] for i in range(n)))
    consumer = tk.MemoryConsumer(
        broker, "t4", group_id="s4",
        assignment=tk.partitions_for_process("t4", 4, 0, 1),
    )
    params = resnet.init_params(jax.random.key(0))

    @jax.jit
    def infer(imgs):
        return jnp.argmax(
            resnet.forward(params, resnet.preprocess(imgs, out_size)), axis=-1
        )

    jax.block_until_ready(infer(jnp.zeros((batch, h, w, 3), jnp.uint8)))
    with tk.KafkaStream(
        consumer, tk.png_images(h, w), batch_size=batch,
        to_device=True, idle_timeout_ms=2000, owns_consumer=True,
    ) as stream:
        rows, elapsed = _drain(stream, lambda b: infer(b.data), n)

    # Decode/infer split, each measured standalone on one batch's worth.
    from torchkafka_tpu import native

    chunk = (payloads * -(-batch // len(payloads)))[:batch]
    t0 = _time.perf_counter()
    native.decode_png_rgb(chunk, h, w)
    decode_ms = (_time.perf_counter() - t0) * 1e3
    imgs_dev = jnp.asarray(np.zeros((batch, h, w, 3), np.uint8))
    int(infer(imgs_dev)[0])  # warm with this exact sharding
    t0 = _time.perf_counter()
    int(infer(imgs_dev)[0])  # strict: scalar fetch
    infer_ms = (_time.perf_counter() - t0) * 1e3

    # Chained on-device iterations (VERDICT r3 item 2): the single-dispatch
    # number above bundles the transport round-trip with compute — honest
    # as "what one poll-to-answer costs" but useless for judging the conv
    # stack. Two chain lengths run the forward in ONE dispatch each, every
    # iteration data-dependent on the last (the label sum perturbs the next
    # input, so XLA cannot hoist them); the SLOPE between the two timings
    # cancels the constant dispatch+fetch overhead that otherwise floors
    # any divide-by-K estimate (~90 ms/call here — 8 chained iterations
    # still read ~12 ms/iter of pure overhead). Conv MFU uses the analytic
    # ResNet-50 count (2·4.089 GFLOP/image at 224², scaled by resolution);
    # XLA's cost analysis counts a fori_loop body once, not per trip.
    def _chained(k):
        def fn(imgs):
            def body(_, carry):
                s, _lab = carry
                x = imgs + (s % 2).astype(imgs.dtype)
                lab = jnp.argmax(
                    resnet.forward(params, resnet.preprocess(x, out_size)),
                    axis=-1,
                ).astype(jnp.int32)
                return jnp.sum(lab).astype(jnp.int32), lab

            from jax import lax as _lax

            return _lax.fori_loop(
                0, k, body,
                (jnp.int32(0), jnp.zeros((imgs.shape[0],), jnp.int32)),
            )[0]

        return jax.jit(fn)

    extra_infer: dict = {}
    if jax.default_backend() == "tpu":
        from torchkafka_tpu.utils.timing import two_point_slope

        k_short, k_long = 8, 40
        fns = {k: _chained(k) for k in (k_short, k_long)}
        for fn in fns.values():
            int(fn(imgs_dev))  # warm/compile both chain lengths first
        # Interleave short/long timings so transport drift between the
        # two chain lengths cannot flip the slope's sign.
        shorts, longs = [], []
        for _ in range(3):
            t0 = _time.perf_counter()
            int(fns[k_short](imgs_dev))
            shorts.append(_time.perf_counter() - t0)
            t0 = _time.perf_counter()
            int(fns[k_long](imgs_dev))
            longs.append(_time.perf_counter() - t0)
        per_iter_s, overhead_s, slope_ok = two_point_slope(
            float(np.median(shorts)), float(np.median(longs)),
            k_short, k_long,
        )
        flops = 2 * 4.089e9 * batch * (out_size / 224) ** 2
        extra_infer = {
            "slope_ok": slope_ok,
            "dispatch_overhead_ms": round(overhead_s * 1e3, 1),
            "conv_flops_per_batch_g": round(flops / 1e9, 1),
        }
        if slope_ok:
            extra_infer.update({
                "device_infer_ms_chained": round(per_iter_s * 1e3, 2),
                "tunnel_share_pct": round(
                    100 * (1 - per_iter_s * 1e3 / infer_ms), 1
                ) if infer_ms else None,
                "conv_mfu_pct": round(100 * flops / per_iter_s / 197e12, 1),
            })
        else:
            # Drift swamped the slope — flag, don't fabricate.
            extra_infer.update({
                "device_infer_ms_chained": None,
                "tunnel_share_pct": None,
                "conv_mfu_pct": None,
            })
    return _result(
        "4:png-resnet-infer", rows, elapsed, stream,
        {
            "image": f"png {h}x{w}->{out_size}",
            "png_bytes_avg": round(png_bytes),
            "compression": round(h * w * 3 / png_bytes, 2),
            "native_decode": native.available(),
            "host_decode_ms_per_batch": round(decode_ms, 2),
            "device_infer_ms_per_batch": round(infer_ms, 2),
            **extra_infer,
        },
    )


def _serving_model(size: str, model_scale: str | None, prompt_len: int,
                   max_new: int, quantized: bool | None = None):
    """(cfg, params, label) for the serving scenarios. ``model_scale`` is
    the VERDICT-r3 scale flag: None keeps the historical tiny/45m configs
    (comparable across rounds); '45m' | '1b' | '8b' draws from the model
    zoo at true serving bytes — '8b' in int8 (the only way 8B fits one
    16 GB chip), the rest bf16 params (so counted bytes == streamed
    bytes in the rooflines). ``quantized`` overrides the per-scale
    default (--quantized serves ANY scale weight-only int8 — decode is
    bytes-bound, so halving bytes vs bf16 raises the roofline
    ceiling); it requires a model_scale, and '8b' cannot un-quantize
    (validated here so direct scenario_5/7 calls get the same guards as
    the CLI)."""
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models import TransformerConfig
    from torchkafka_tpu.models.transformer import init_params

    if quantized is not None and model_scale is None:
        raise ValueError(
            "quantized requires a model_scale (the tiny/default configs "
            "ignore dtype knobs; accepting it would silently serve bf16)"
        )
    if model_scale is None:
        cfg = (
            TransformerConfig(vocab_size=512, d_model=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, d_ff=128,
                              max_seq_len=prompt_len + max_new,
                              dtype=jnp.float32)
            if size == "tiny"
            else TransformerConfig(max_seq_len=prompt_len + max_new)
        )
        return cfg, init_params(jax.random.key(0), cfg), "default"
    import sys
    import time as _time

    from torchkafka_tpu.models.zoo import random_serving_params, zoo_config

    cfg = zoo_config(model_scale, max_seq_len=prompt_len + max_new)
    if quantized is None:
        quantized = model_scale == "8b"
    elif model_scale == "8b" and not quantized:
        raise ValueError(
            "8b serves int8 only: bf16 8B params are ~16 GB and cannot fit "
            "one 16 GB chip next to the KV pool (and '8b' labels int8 in "
            "every published table)"
        )
    t0 = _time.perf_counter()
    params = random_serving_params(
        jax.random.key(0), cfg, quantized=quantized
    )
    jax.block_until_ready(params)
    label = f"{model_scale}-int8" if quantized and model_scale != "8b" else model_scale
    print(
        f"[scale {label}] params materialised in "
        f"{_time.perf_counter() - t0:.1f}s",
        file=sys.stderr, flush=True,
    )
    return cfg, params, label


def scenario_5(
    size: str = "tiny", model_scale: str | None = None,
    quantized: bool | None = None,
) -> dict:
    """Prompt topic → KV-cache generation → commit offsets only after the
    whole generation retires (BASELINE config 5; no reference analog).
    ``model_scale`` (45m | 1b | 8b) serves the zoo models at true HBM
    footprint and adds device-side decode timing with an HBM roofline %
    (prefill measured separately — it is compute-bound, decode is
    bandwidth-bound; folding them together hides which one you are)."""
    import time as _time

    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk
    from torchkafka_tpu.models.generate import generate, prefill
    from torchkafka_tpu.models.zoo import params_nbytes

    prompt_len, max_new = (16, 8) if size == "tiny" else (128, 64)
    n, batch = (64, 8) if size == "tiny" else (1024, 32)
    if model_scale == "1b":
        n, batch = 128, 16
    elif model_scale == "8b":
        n, batch = 48, 16
    cfg, params, label = _serving_model(
        size, model_scale, prompt_len, max_new, quantized
    )
    broker = tk.InMemoryBroker()
    broker.create_topic("t5", partitions=2)
    rng = np.random.default_rng(0)
    broker.produce_many(
        "t5",
        (rng.integers(0, cfg.vocab_size, prompt_len, dtype=np.int32).tobytes()
         for _ in range(n)),
    )
    consumer = tk.MemoryConsumer(
        broker, "t5", group_id="s5",
        assignment=tk.partitions_for_process("t5", 2, 0, 1),
    )
    gen = jax.jit(lambda p, t: generate(p, cfg, t, max_new))
    jax.block_until_ready(gen(params, jnp.zeros((batch, prompt_len), jnp.int32)))
    generated = []

    def step(b):
        out = gen(params, b.data)
        generated.append(out)
        return out

    with tk.KafkaStream(
        consumer, tk.fixed_width(prompt_len, np.int32), batch_size=batch,
        to_device=True, idle_timeout_ms=2000, owns_consumer=True,
    ) as stream:
        rows, elapsed = _drain(stream, step, n)
    toks = rows * max_new
    extra = {
        "model_scale": label,
        "params_bytes_g": round(params_nbytes(params) / 1e9, 3),
        "generated_tokens": toks,
        "tokens_per_s": round(toks / elapsed, 1) if elapsed else None,
    }
    if model_scale is not None and jax.default_backend() == "tpu":
        # Device-side split: prefill alone, then whole-generate, both as
        # median-of-3 strict-fetch timings; decode tok/s and its roofline
        # come from the difference. Large models run long enough per call
        # that dispatch jitter is noise here.
        toks_dev = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, prompt_len)), jnp.int32
        )
        pf = jax.jit(lambda p, t: prefill(p, cfg, t, prompt_len + max_new)[0])
        float(jax.device_get(pf(params, toks_dev)[0, 0]))  # warm/compile
        pf_times, gen_times = [], []
        for _ in range(3):
            t0 = _time.perf_counter()
            out = pf(params, toks_dev)
            float(jax.device_get(out[0, 0]))  # scalar fetch, not [B, V]
            pf_times.append(_time.perf_counter() - t0)
            t0 = _time.perf_counter()
            out = gen(params, toks_dev)
            int(jax.device_get(out[0, 0]))
            gen_times.append(_time.perf_counter() - t0)
        pf_s, gen_s = float(np.median(pf_times)), float(np.median(gen_times))
        from torchkafka_tpu.serve import V5E_PEAK_HBM_GBS, decode_tick_bytes

        w_bytes, kv_bytes = decode_tick_bytes(
            params, cfg, batch, prompt_len + max_new
        )
        roofline_tok_s = (
            batch * V5E_PEAK_HBM_GBS * 1e9 / (w_bytes + kv_bytes)
        )
        extra.update({
            "device_prefill_ms": round(pf_s * 1e3, 1),
            "device_generate_ms": round(gen_s * 1e3, 1),
            "roofline_tok_s": round(roofline_tok_s, 1),
        })
        decode_s = gen_s - pf_s
        if decode_s <= 0.25 * gen_s:
            # Both timings are single dispatches through the tunnel whose
            # wall is max(round-trip, device work) — NOT their sum — so
            # the difference carries no information once the device work
            # sits under the ~60-140 ms round trip (the 45M scale: both
            # walls read ≈RTT and the delta is jitter; observed readings
            # of 2e12 and 2.3e6 tok/s in consecutive runs). Flag unless
            # decode dominates the generate wall, like two_point_slope's
            # slope_ok — scenario 7's fori-chained decode_roofline is the
            # robust decode number at every scale.
            extra.update({
                "split_ok": False,
                "device_decode_tok_s": None,
                "hbm_roofline_pct": None,
            })
        else:
            decode_tok_s = batch * max_new / decode_s
            extra.update({
                "split_ok": True,
                "device_decode_tok_s": round(decode_tok_s, 1),
                "hbm_roofline_pct": round(
                    100 * decode_tok_s / roofline_tok_s, 1
                ),
            })
    return _result("5:generate", rows, elapsed, stream, extra)


def scenario_7(
    size: str = "tiny", model_scale: str | None = None,
    serve_eos: bool = False, quantized: bool | None = None,
    kv_int8: bool = False, kv_kernel: bool | str = "auto",
    spec: bool = False, spec_k: int = 4,
    spec_draft_layers: int | None = None,
    temperature: float = 0.0, top_k: int | None = None,
    top_p: float | None = None,
) -> dict:
    """Continuous-batching serving (serve.StreamingGenerator): same prompt
    topic shape as scenario 5, but slots recycle as generations hit EOS —
    an EOS id picked from a probe generation so a real fraction of prompts
    stops early. Reports completions/s and tokens/s; offsets commit per
    completion through the interval ledger. (No reference analog.)

    ``model_scale`` (45m | 1b | 8b): serve the zoo models at true HBM
    footprint, adding ``decode_roofline`` — pure device decode tok/s
    against the HBM-bandwidth bound, the serving analog of MFU. EOS is
    off at scale BY DEFAULT (every slot runs full max_new, one dispatch
    per generation — the throughput ceiling, directly comparable to the
    roofline); ``serve_eos=True`` (--serve-eos) turns it ON at scale with
    ``ticks_per_sync=8``, so completed slots readmit MID-generation-block
    — the continuous-batching row (VERDICT r4 weak #4), with
    ``readmissions`` counting slots refilled while others were in
    flight and ``truncated_by_eos`` proving early stops.

    ``spec`` (--spec): serve through ``SpecStreamingGenerator`` — the
    layer-truncated self-draft proposes ``spec_k`` tokens per slot per
    round, one multi-query verify advances every slot by its accepted
    length. Token-exact vs the plain path by construction (greedy), so
    the row reports the same completions plus the MEASURED acceptance
    (``spec_stats``). ``spec_draft_layers`` defaults to half the
    target's layers."""
    import time as _time

    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk
    from torchkafka_tpu.models.generate import generate
    from torchkafka_tpu.serve import StreamingGenerator

    prompt_len, max_new = (16, 8) if size == "tiny" else (128, 64)
    n, slots = (24, 8) if size == "tiny" else (512, 32)
    if model_scale == "1b":
        n, slots = 128, 16
    elif model_scale == "8b":
        n, slots = 48, 16
    cfg, params, label = _serving_model(
        size, model_scale, prompt_len, max_new, quantized
    )
    broker = tk.InMemoryBroker()
    broker.create_topic("t7", partitions=2)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len), dtype=np.int32)
    for i in range(n):
        broker.produce("t7", prompts[i].tobytes(), partition=i % 2)
    if model_scale is None or serve_eos:
        # Probe a few lockstep continuations and use the MODAL generated
        # token as EOS: random-init models repeat attractor tokens, so the
        # mode truncates a meaningful fraction of the stream and visibly
        # exercises slot recycling (decode positions >= 1 only; prefill's
        # token 0 is emitted unconditionally, matching the server's EOS
        # rule).
        probe = np.asarray(
            jax.jit(lambda p, t: generate(p, cfg, t, max_new))(
                params, jnp.asarray(prompts[:8])
            )
        )
        toks, counts = np.unique(probe[:, 1:], return_counts=True)
        eos_id = int(toks[counts.argmax()])
    else:
        eos_id = None

    consumer = tk.MemoryConsumer(broker, "t7", group_id="s7")
    if spec:
        from torchkafka_tpu.serve_spec import SpecStreamingGenerator

        # A speculative round advances a slot by 1..spec_k+1 tokens, so a
        # full-accept generation completes in ceil((max_new-1)/(k+1))
        # rounds; block at that length — low-acceptance streams just take
        # more blocks through the host loop.
        ticks_per_sync = max(1, -(-(max_new - 1) // (spec_k + 1)))
        server = SpecStreamingGenerator(
            consumer, params, cfg, slots=slots, prompt_len=prompt_len,
            max_new=max_new, eos_id=eos_id, commit_every=slots,
            k=spec_k, draft_layers=spec_draft_layers,
            ticks_per_sync=ticks_per_sync,
        )
    else:
        ticks_per_sync = (
            max(1, max_new - 1) if eos_id is None
            else (8 if model_scale is not None else max(1, max_new // 2))
        )
        server = StreamingGenerator(
            consumer, params, cfg, slots=slots, prompt_len=prompt_len,
            max_new=max_new, eos_id=eos_id, commit_every=slots,
            kv_dtype="int8" if kv_int8 else None,
            kv_kernel=kv_kernel,
            # --temperature/--top-k/--top-p: the sampled serving path
            # (models.generate.sample_logits — static-shape top-k/nucleus).
            temperature=temperature, top_k=top_k, top_p=top_p,
            # Dispatch + sync latency dominate per-token syncing on tunneled
            # transports. With EOS off at scale, ONE dispatch per generation
            # is strictly better (max_new - 1: prefill emits token 0, so a
            # generation completes after max_new - 1 decode ticks — a
            # max_new-tick block would spend its last tick fully
            # done-latched). With EOS on: at scale, 8-tick blocks bound how
            # long a completed slot idles before readmission (the
            # continuous-batching row); tiny sizes keep half-generation
            # blocks.
            ticks_per_sync=ticks_per_sync,
        )
    import sys
    import time as _wt

    _t0 = _wt.perf_counter()
    server.warmup()  # compile outside the timed region, like scenario 5
    if model_scale is not None:
        print(
            f"[scale {model_scale}] serve warmup (admit+tick compile) in "
            f"{_wt.perf_counter() - _t0:.1f}s",
            file=sys.stderr, flush=True,
        )
    # No roofline probe on the spec server: it runs LIVE speculative
    # rounds, which would pollute the measured acceptance counters (and
    # its byte accounting is target-only — see serve_spec._build).
    roofline = (
        server.decode_roofline()
        if model_scale is not None and not spec
        and jax.default_backend() == "tpu"
        else {}
    )
    if roofline:
        print(f"[scale {model_scale}] roofline: {roofline}",
              file=sys.stderr, flush=True)
    toks = 0
    done = 0
    truncated = 0
    t0 = _time.perf_counter()
    for _rec, out in server.run(max_records=n):
        toks += int(out.shape[0])
        done += 1
        truncated += int(out.shape[0] < max_new)
    elapsed = _time.perf_counter() - t0
    consumer.close()
    committed = sum(
        broker.committed("s7", tk.TopicPartition("t7", p)) or 0 for p in (0, 1)
    )
    return {
        "scenario": "7:continuous-serve" + ("+spec" if spec else ""),
        "model_scale": label,
        **({"spec": server.spec_stats()} if spec else {}),
        "records": done,
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(done / elapsed, 1) if elapsed else None,
        "generated_tokens": toks,
        "tokens_per_s": round(toks / elapsed, 1) if elapsed else None,
        "truncated_by_eos": truncated,
        "readmissions": server.metrics.readmissions.count,
        "eos_mode": "on" if eos_id is not None else "off(one-dispatch)",
        **({"sampling": {
            "temperature": temperature, "top_k": top_k, "top_p": top_p,
        }} if (temperature != 0.0 or top_k is not None or top_p is not None)
            else {}),
        "ticks_per_sync": ticks_per_sync,
        "kv_dtype": "int8" if kv_int8 else "compute",
        "kv_kernel": server._kv_kernel,
        "slots": slots,
        "committed": committed,
        "commit_failures": server.metrics.commit_failures.count,
        "dropped": server.metrics.dropped.count,
        "commit": server.metrics.commit_latency.summary(),
        **roofline,
    }


def scenario_10(size: str = "tiny", replicas: int = 2) -> dict:
    """Serving fleet (torchkafka_tpu/fleet): N replicas as one consumer
    group over the prompt topic, QoS admission in front (two tenants —
    one token-bucket rate-limited — and both priority lanes), finished by
    a mid-run graceful drain plus a restarted fleet serving the remainder
    with zero replayed completions. The tier-1 smoke for the fleet's
    admission + drain paths: tiny model, seconds on CPU; the throughput
    story lives in benchmarks/bench_fleet.py."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import QoSConfig, ServingFleet

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 24 if size == "tiny" else 128
    parts = 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    broker = tk.InMemoryBroker()
    broker.create_topic("t10", partitions=parts)
    rng = np.random.default_rng(0)
    # KEYED production (no explicit partition): tenants land on disjoint
    # partitions via the key hash, and the lane rides the tenant. That
    # per-partition homogeneity is what keeps admission FIFO per partition
    # — the invariant the replay-free drain depends on (QoS reordering
    # WITHIN a partition trades drain replay-freedom for priority; see the
    # fleet README section). crc32: 'throttled'→p3, 'open'→p0 of 4.
    produced: list[tuple[int, int]] = []
    for i in range(n):
        key = b"throttled" if i % 3 == 0 else b"open"
        rec = broker.produce(
            "t10",
            rng.integers(0, cfg.vocab_size, prompt_len,
                         dtype=np.int32).tobytes(),
            key=key,
            headers=(
                ("lane", b"batch" if key == b"throttled" else b"interactive"),
            ),
        )
        produced.append((rec.partition, rec.offset))
    qos = QoSConfig(
        # Low enough that the throttled tenant provably queues behind its
        # bucket during the run, high enough that the smoke stays fast.
        tenant_rates={"throttled": 4.0}, burst=1.0,
        max_queue_depth=64, resume_queue_depth=16,
    )

    def build(group_stage_kw):
        return ServingFleet(
            lambda rid: tk.MemoryConsumer(broker, "t10", group_id="s10"),
            params, cfg, replicas=replicas, prompt_len=prompt_len,
            max_new=max_new, slots=4, qos=qos, **group_stage_kw,
        )

    fleet = build({"commit_every": 4})
    fleet.warmup()
    t0 = _time.perf_counter()
    run1: list = []
    for item in fleet.serve(idle_timeout_ms=2000):
        run1.append(item)
        if len(run1) == n // 2:
            fleet.drain()  # graceful: finish in-flight, commit, leave
    drained_states = [rep.state for rep in fleet.replicas]
    fleet2 = build({"commit_every": 4})
    run2 = fleet2.serve_all(idle_timeout_ms=2000)
    fleet2.close()
    elapsed = _time.perf_counter() - t0
    keys1 = {(r.partition, r.offset) for _rid, r, _t in run1}
    keys2 = {(r.partition, r.offset) for _rid, r, _t in run2}
    s = fleet.metrics.summary(fleet.replicas)
    done = len(run1) + len(run2)
    gens = [rep.gen for rep in fleet.replicas + fleet2.replicas]
    return {
        "scenario": "10:serving-fleet",
        "model_scale": label,
        "replicas": replicas,
        "records": done,
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(done / elapsed, 1) if elapsed else None,
        "drained_states": drained_states,
        "drains": s["drains"],
        "coverage_complete": keys1 | keys2 == set(produced),
        "zero_replayed_after_drain": not (keys1 & keys2),
        "tenants": s["tenants"],
        "lanes": {
            lane: {"p50_ms": round(v["p50_ms"], 3), "count": v["count"]}
            for lane, v in s["lanes"].items()
        },
        "backpressure_pauses": s["backpressure_pauses"],
        "commit": s["commit"],
        "commit_failures": sum(
            g.metrics.commit_failures.count for g in gens
        ),
        "dropped": sum(g.metrics.dropped.count for g in gens),
    }


def scenario_11(size: str = "tiny", replicas: int = 2) -> dict:
    """Chaos-soak smoke (torchkafka_tpu/resilience): a 2-replica serving
    fleet over ``ResilientConsumer(ChaosConsumer(MemoryConsumer))`` hits
    a broker-outage window mid-serve plus one poisoned (corrupted)
    prompt. The circuit must open then close (metrics-observable), every
    non-poisoned prompt must complete exactly once with the committed
    watermark at every log end, and the poisoned prompt must land in the
    DLQ topic with an acknowledged produce — the resilience layer's
    tier-1 guard, seconds on CPU; the full differential lives in
    tests/test_resilience.py."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ServingFleet
    from torchkafka_tpu.resilience import (
        CLOSED, CircuitBreaker, PoisonQuarantine, ResilientConsumer,
        RetryPolicy,
    )
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n, parts = (16, 4) if size == "tiny" else (96, 4)
    poison = ("t11", 2, 1)  # (topic, partition, offset) of the bad prompt
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    broker = tk.InMemoryBroker()
    broker.create_topic("t11", partitions=parts)
    broker.create_topic("t11-dlq", partitions=1)
    rng = np.random.default_rng(0)
    produced = []
    for i in range(n):
        rec = broker.produce(
            "t11",
            rng.integers(0, cfg.vocab_size, prompt_len,
                         dtype=np.int32).tobytes(),
            partition=i % parts,
        )
        produced.append((rec.partition, rec.offset))
    quarantine = PoisonQuarantine(
        tk.MemoryProducer(broker), "t11-dlq", budget=2
    )
    chaos_list, rc_list = [], []

    def factory(rid):
        chaos = tk.ChaosConsumer(
            tk.MemoryConsumer(broker, "t11", group_id="s11"),
            seed=rid,
            outages=[(6, 6)],  # ops 6-11: poll AND commit raise
            corrupt_offsets={poison},
        )
        rc = ResilientConsumer(
            chaos,
            policy=RetryPolicy(
                max_attempts=2, base_delay_s=0.001, max_delay_s=0.002,
                deadline_s=5.0, seed=rid,
            ),
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.02),
        )
        chaos_list.append(chaos)
        rc_list.append(rc)
        return rc

    fleet = ServingFleet(
        factory, params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=max_new, slots=2, commit_every=4,
        gen_kwargs={"quarantine": quarantine},
    )
    fleet.warmup()
    t0 = _time.perf_counter()
    served: list = []
    served_during_open = 0
    for _rid, rec, _toks in fleet.serve(idle_timeout_ms=2000):
        if any(rc.breaker.state != CLOSED for rc in rc_list):
            served_during_open += 1
        served.append((rec.partition, rec.offset))
    # Settle: cadence commits that failed survivably during the outage
    # stay pending (pending_commit > 0); retry against the healed broker.
    deadline = _time.monotonic() + 10.0
    while any(rep.gen.pending_commit for rep in fleet.replicas):
        for rep in fleet.replicas:
            if rep.gen.pending_commit:
                rep.gen.flush_commits()
        if _time.monotonic() > deadline:
            break
        _time.sleep(0.005)
    fleet.close()
    elapsed = _time.perf_counter() - t0
    expect = {(p, o) for p, o in produced if ("t11", p, o) != poison}
    committed_complete = all(
        broker.committed("s11", TopicPartition("t11", p))
        == broker.end_offset(TopicPartition("t11", p))
        for p in range(parts)
    )
    gens = [rep.gen for rep in fleet.replicas]
    return {
        "scenario": "11:chaos-soak",
        "model_scale": label,
        "replicas": replicas,
        "records": len(served),
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(len(served) / elapsed, 1) if elapsed else None,
        "exactly_once": set(served) == expect and len(served) == len(expect),
        "duplicates": fleet.metrics.duplicates.count,
        "committed_complete": committed_complete,
        "dlq_records": broker.end_offset(TopicPartition("t11-dlq", 0)),
        "quarantined": sum(g.metrics.quarantined.count for g in gens),
        "served_during_open": served_during_open,
        "outage_faults": sum(c.injected_outage_faults for c in chaos_list),
        "retries": sum(rc.metrics.retries.count for rc in rc_list),
        "circuit_opens": sum(rc.metrics.circuit_opens.count for rc in rc_list),
        "circuit_closes": sum(
            rc.metrics.circuit_closes.count for rc in rc_list
        ),
        "commit_failures": sum(
            g.metrics.commit_failures.count for g in gens
        ),
        "dropped": sum(g.metrics.dropped.count for g in gens),
    }


def scenario_12(size: str = "tiny", replicas: int = 2) -> dict:
    """Prefix-cache serving smoke (torchkafka_tpu/kvcache): a
    DUPLICATE-HEAVY prompt topic — three tenants, each with a fixed
    system prompt prefix, keyed production routing every tenant to one
    partition ('alpha'→p2, 'beta'→p3, 'gamma'→p1 of 4 via crc32, the
    scenario-10 keying idiom) — through a 2-replica fleet whose
    generators run the PAGED pool with radix prefix reuse
    (``kv_pages=``). Per replica, only each tenant's FIRST prompt pays a
    full prefill; every later one links the cached system-prompt blocks
    and prefills the suffix. The tier-1 guard for the cache-on fleet
    path: coverage + commit exactness (token-exactness vs cache-off is
    tests/test_kvcache.py's differential; the throughput/memory story is
    benchmarks/bench_kvcache.py)."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ServingFleet
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 24 if size == "tiny" else 128
    block = 4 if size == "tiny" else 16
    sys_len = 3 * block  # tenant system prompt: 3 whole shareable blocks
    parts = 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    broker = tk.InMemoryBroker()
    broker.create_topic("t12", partitions=parts)
    rng = np.random.default_rng(0)
    tenants = ("alpha", "beta", "gamma")
    system = {
        t: rng.integers(0, cfg.vocab_size, sys_len, dtype=np.int32)
        for t in tenants
    }
    produced = []
    for i in range(n):
        t = tenants[i % len(tenants)]
        prompt = np.concatenate([
            system[t],
            rng.integers(0, cfg.vocab_size, prompt_len - sys_len,
                         dtype=np.int32),
        ])
        rec = broker.produce("t12", prompt.tobytes(), key=t.encode())
        produced.append((rec.partition, rec.offset))
    slots = 4
    pages = {
        "block_size": block,
        # Per-replica pool: all slots' worst case + sink + cache headroom
        # for the three tenants' system prompts.
        "num_blocks": slots * -(-(prompt_len + max_new) // block) + 16,
    }
    fleet = ServingFleet(
        lambda rid: tk.MemoryConsumer(broker, "t12", group_id="s12"),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=max_new, slots=slots, commit_every=4,
        gen_kwargs={"kv_pages": pages},
    )
    fleet.warmup()
    t0 = _time.perf_counter()
    served = fleet.serve_all(idle_timeout_ms=2000)
    elapsed = _time.perf_counter() - t0
    keys = {(r.partition, r.offset) for _rid, r, _t in served}
    committed_complete = all(
        broker.committed("s12", TopicPartition("t12", rec_p))
        == broker.end_offset(TopicPartition("t12", rec_p))
        for rec_p in {p for p, _ in produced}
    )
    s = fleet.metrics.summary(fleet.replicas)
    cache = s["prefix_cache"]
    gens = [rep.gen for rep in fleet.replicas]
    fleet.close()
    return {
        "scenario": "12:prefix-cache-fleet",
        "model_scale": label,
        "replicas": replicas,
        "records": len(served),
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(len(served) / elapsed, 1) if elapsed else None,
        "coverage_complete": keys == set(produced),
        "committed_complete": committed_complete,
        "tenants": len(tenants),
        "system_prompt_tokens": sys_len,
        "cache": cache,
        "prefill_tokens": cache["prefill_tokens"],
        "prefill_tokens_dense": n * prompt_len,
        "prefill_savings_pct": round(
            100 * (1 - cache["prefill_tokens"] / (n * prompt_len)), 1
        ),
        "commit_failures": sum(
            g.metrics.commit_failures.count for g in gens
        ),
        "dropped": sum(g.metrics.dropped.count for g in gens),
    }


def scenario_13(size: str = "tiny", replicas: int = 2) -> dict:
    """Warm-failover smoke (torchkafka_tpu/journal): a 2-replica fleet
    with per-replica decode journals, a SEEDED mid-generation replica
    kill (ReplicaChaos), and the survivor warm-resuming the victim's
    in-flight prompts from its on-disk journal. Audited against a
    no-kill reference fleet over the same prompts: coverage total,
    commits complete, completions BYTE-IDENTICAL record-for-record
    (duplicates allowed, divergence not), and the journal provably used
    (warm resumes + journal-served > 0). The full cadence/mode
    differential is tests/test_journal.py; the re-decoded-token savings
    story is benchmarks/bench_fleet.py --failover."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ReplicaChaos, ServingFleet
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 16 if size == "tiny" else 64
    parts = 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)

    def build(group: str):
        broker = tk.InMemoryBroker()
        broker.create_topic("t13", partitions=parts)
        for i in range(n):
            broker.produce("t13", prompts[i].tobytes(), partition=i % parts)
        return broker

    def serve(broker, group, journal_dir, chaos):
        fleet = ServingFleet(
            lambda rid: tk.MemoryConsumer(broker, "t13", group_id=group),
            params, cfg, replicas=replicas, prompt_len=prompt_len,
            max_new=max_new, slots=2,
            # A large cadence keeps the victim's completions uncommitted,
            # so the kill provably exercises redelivery + warm resume.
            commit_every=100,
            journal_dir=journal_dir, journal_cadence=1,
        )
        fleet.warmup()
        got: dict = {}
        duplicates_identical = True
        for _rid, rec, toks in fleet.serve(idle_timeout_ms=2000,
                                           chaos=chaos):
            key = (rec.partition, rec.offset)
            if key in got and not np.array_equal(got[key], toks):
                duplicates_identical = False
            got[key] = toks
        for rep in fleet.replicas:
            if rep.runnable:
                rep.gen.flush_commits()
        summary = fleet.metrics.summary(fleet.replicas)
        fleet.close()
        return got, summary, duplicates_identical

    with tempfile.TemporaryDirectory() as td:
        ref, _, _ = serve(build("ref13"), "ref13", None, None)
        t0 = _time.perf_counter()
        chaos = ReplicaChaos(seed=5, min_completions=2, max_completions=5)
        broker = build("s13")
        got, s, dup_ok = serve(
            broker, "s13", os.path.join(td, "journals"), chaos
        )
        elapsed = _time.perf_counter() - t0
        committed_complete = all(
            broker.committed("s13", TopicPartition("t13", p))
            == broker.end_offset(TopicPartition("t13", p))
            for p in range(parts)
        )
    identical = set(got) == set(ref) and all(
        np.array_equal(got[k], ref[k]) for k in ref
    )
    jn = s["journal"]
    return {
        "scenario": "13:warm-failover",
        "model_scale": label,
        "replicas": replicas,
        "records": len(got),
        "elapsed_s": round(elapsed, 3),
        "killed": chaos.killed,
        "replica_deaths": s["replica_deaths"],
        "coverage_complete": set(got) == set(ref),
        "committed_complete": committed_complete,
        "identical_to_no_kill": identical,
        "duplicates_identical": dup_ok,
        "journal_handoffs": jn["handoffs"],
        "warm_resumes": jn["warm_resumes"],
        "tokens_restored": jn["tokens_restored"],
        "served_from_journal": jn["served_from_journal"],
        "resume_rejected": jn["resume_rejected"],
    }


def scenario_14(size: str = "tiny", prefill_chunk: int | None = None) -> dict:
    """Chunked-prefill prompt-storm smoke (serve.py kv_pages chunked
    mode): a 4x-oversubscribed admission wave — duplicate-heavy tenant
    prompts, all produced up front — through a paged server whose
    admission is CHUNKED into the decode tick (one static program per
    tick carrying a bounded chunk of queued suffix tokens alongside all
    decode slots). The tier-1 guard for the PR-6 latency property:
    decode inter-token latency must stay EXACTLY one tick per token for
    every in-flight slot while the storm drains FIFO through the chunk
    queue (``max_decode_stall_ticks == 0``), with coverage/commit
    exactness and the chunk counters live. ``prefill_chunk`` defaults
    to one block per tick — small enough that the storm provably queues
    (admission_stall_ticks > 0). The exactness differential across
    chunk widths is tests/test_kvcache.py; the wall-clock story is
    benchmarks/bench_kvcache.py --chunk."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    block = 4 if size == "tiny" else 16
    slots = 4
    n = 4 * slots  # the 4x storm
    chunk = prefill_chunk if prefill_chunk else block
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    broker = tk.InMemoryBroker()
    broker.create_topic("t14", partitions=4)
    rng = np.random.default_rng(0)
    sys_len = 2 * block
    system = rng.integers(0, cfg.vocab_size, sys_len, dtype=np.int32)
    for i in range(n):
        prompt = np.concatenate([
            system,
            rng.integers(0, cfg.vocab_size, prompt_len - sys_len,
                         dtype=np.int32),
        ])
        broker.produce("t14", prompt.tobytes(), partition=i % 4)

    activation: dict = {}
    act_order: list = []
    enq_order: list = []

    class Instrumented(StreamingGenerator):
        def admit_records(self, records):
            before = len(self._prefill_queue)
            out = super().admit_records(records)
            enq_order.extend(
                (e.rec.partition, e.rec.offset)
                for e in self._prefill_queue[before:]
            )
            return out

        def _activate_chunk_finishers(self, finishers):
            for e, _row in finishers:
                key = (e.rec.partition, e.rec.offset)
                activation[key] = self._tick_counter
                act_order.append(key)
            super()._activate_chunk_finishers(finishers)

    consumer = tk.MemoryConsumer(broker, "t14", group_id="s14")
    server = Instrumented(
        consumer, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=4, ticks_per_sync=1,
        kv_pages={
            "block_size": block,
            "num_blocks": slots * -(-(prompt_len + max_new) // block) + 12,
            "prefill_chunk": chunk,
        },
    )
    server.warmup()
    t0 = _time.perf_counter()
    completion: dict = {}
    for rec, toks in server.run(max_records=n):
        completion[(rec.partition, rec.offset)] = (
            server._tick_counter, int(np.asarray(toks).shape[0])
        )
    elapsed = _time.perf_counter() - t0
    committed_complete = all(
        broker.committed("s14", TopicPartition("t14", p))
        == broker.end_offset(TopicPartition("t14", p))
        for p in range(4)
    )
    # Zero decode stall: each record's decode span is exactly its token
    # count minus the activation tick's token 0.
    stalls = [
        done_tick - activation[k] - (n_toks - 1)
        for k, (done_tick, n_toks) in completion.items()
    ]
    m = server.metrics
    cs = m.chunk_summary()
    cache = m.cache_summary()
    consumer.close()
    return {
        "scenario": "14:chunked-prefill-storm",
        "model_scale": label,
        "records": len(completion),
        "elapsed_s": round(elapsed, 3),
        "storm_factor": n // slots,
        "prefill_chunk": chunk,
        "coverage_complete": len(completion) == n,
        "committed_complete": committed_complete,
        "max_decode_stall_ticks": max(stalls) if stalls else None,
        "fifo_activation": act_order == enq_order,
        "chunk_ticks": cs["chunk_ticks"],
        "prefill_tokens_per_tick": cs["prefill_tokens_per_tick"],
        "admission_stall_ticks": cs["stall_ticks"],
        "chunk_utilization": cs["utilization"],
        "queue_tokens_end": cs["queue_tokens"],
        "prefix_hit_rate": cache["hit_rate"],
        "prefill_tokens": cache["prefill_tokens"],
        "prefill_tokens_dense": n * prompt_len,
    }


def scenario_15(size: str = "tiny", replicas: int = 2) -> dict:
    """SLO observability smoke (torchkafka_tpu/obs): a keyed-tenant
    2-replica fleet — three tenants on fixed system prompts (the
    scenario-12 cache shape), both QoS lanes — served with the record
    lifecycle tracer on, then the SLO report production watches: per-
    tenant/per-lane time-to-first-token and inter-token-latency p50/p99,
    admission queue wait, e2e poll→commit, and the prefix-cache hit
    rate, all read back from ``FleetMetrics.summary()``. Plus the
    endpoint smoke: a ``MetricsExporter`` on an ephemeral port scraped
    over real HTTP, every metrics class (fleet + per-replica serve +
    SLO tracer) riding the one /metrics exposition. The tier-1 guard
    for the obs stack; trace determinism lives in tests/test_obs.py and
    the overhead numbers in benchmarks/bench_obs.py."""
    import time as _time
    import urllib.request

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import QoSConfig, ServingFleet
    from torchkafka_tpu.obs import MetricsExporter
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 24 if size == "tiny" else 128
    block = 4 if size == "tiny" else 16
    sys_len = 2 * block
    parts = 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    broker = tk.InMemoryBroker()
    broker.create_topic("t15", partitions=parts)
    rng = np.random.default_rng(0)
    tenants = ("alpha", "beta", "gamma")
    system = {
        t: rng.integers(0, cfg.vocab_size, sys_len, dtype=np.int32)
        for t in tenants
    }
    produced = []
    for i in range(n):
        t = tenants[i % len(tenants)]
        prompt = np.concatenate([
            system[t],
            rng.integers(0, cfg.vocab_size, prompt_len - sys_len,
                         dtype=np.int32),
        ])
        rec = broker.produce(
            "t15", prompt.tobytes(), key=t.encode(),
            headers=(
                ("lane", b"interactive" if t == "alpha" else b"batch"),
            ),
        )
        produced.append((rec.partition, rec.offset))
    slots = 4
    pages = {
        "block_size": block,
        "num_blocks": slots * -(-(prompt_len + max_new) // block) + 16,
    }
    fleet = ServingFleet(
        lambda rid: tk.MemoryConsumer(broker, "t15", group_id="s15"),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=max_new, slots=slots, qos=QoSConfig(), commit_every=4,
        gen_kwargs={"kv_pages": pages}, obs=True,
    )
    fleet.warmup()
    t0 = _time.perf_counter()
    served = fleet.serve_all(idle_timeout_ms=2000)
    elapsed = _time.perf_counter() - t0
    keys = {(r.partition, r.offset) for _rid, r, _t in served}
    committed_complete = all(
        broker.committed("s15", TopicPartition("t15", p))
        == broker.end_offset(TopicPartition("t15", p))
        for p in {p for p, _ in produced}
    )
    s = fleet.metrics.summary(fleet.replicas)
    slo = s["slo"]

    def pct(leaf):
        return {
            "count": leaf["count"],
            "p50_ms": round(leaf["p50_ms"], 3),
            "p99_ms": round(leaf["p99_ms"], 3),
        }

    report = {
        t: {
            "ttft": pct(slo["ttft"]["by_tenant"].get(
                t, {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0})),
            "itl": pct(slo["itl"]["by_tenant"].get(
                t, {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0})),
        }
        for t in tenants
    }
    # The endpoint smoke: every metrics class through ONE exposition,
    # scraped over real HTTP on an ephemeral port.
    exporter = MetricsExporter()
    exporter.add(lambda: fleet.metrics.render_prometheus(
        replicas=fleet.replicas))
    for rep in fleet.replicas:
        exporter.add(rep.gen.metrics)
    exporter.add(fleet.tracer)
    with exporter:
        with urllib.request.urlopen(exporter.url, timeout=10) as resp:
            endpoint_status = resp.status
            body = resp.read().decode("utf-8")
    fleet.close()
    fleet.tracer.close()
    trace_summary = fleet.tracer.summary()
    return {
        "scenario": "15:slo-observability",
        "model_scale": label,
        "replicas": replicas,
        "records": len(served),
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(len(served) / elapsed, 1) if elapsed else None,
        "coverage_complete": keys == set(produced),
        "committed_complete": committed_complete,
        "tenant_slo": report,
        "ttft": pct(slo["ttft"]["all"]),
        "itl": pct(slo["itl"]["all"]),
        "queue_wait": pct(slo["queue_wait"]["all"]),
        "e2e": pct(slo["e2e"]["all"]),
        "lanes_observed": sorted(slo["ttft"]["by_lane"]),
        "replicas_observed": sorted(slo["ttft"]["by_replica"]),
        "cache_hit_rate": s["prefix_cache"]["hit_rate"],
        "trace_events": trace_summary["events"],
        "trace_stages": trace_summary["stages"],
        "open_records_end": trace_summary["open_records"],
        "endpoint_status": endpoint_status,
        "endpoint_bytes": len(body),
        "endpoint_series": sum(
            1 for line in body.splitlines()
            if line and not line.startswith("#")
        ),
        "endpoint_has": {
            name: (name in body) for name in (
                "torchkafka_fleet_ttft_ms",
                "torchkafka_fleet_itl_ms",
                "torchkafka_fleet_tenant_admitted_total",
                "torchkafka_serve_tokens_total",
                "torchkafka_slo_trace_events_total",
            )
        },
        "dropped": sum(
            rep.gen.metrics.dropped.count for rep in fleet.replicas
        ),
        "commit_failures": sum(
            rep.gen.metrics.commit_failures.count for rep in fleet.replicas
        ),
    }


def _merge_tenant_cache(metrics_list) -> dict:
    """Per-tenant prefix-cache hit rates merged across replicas
    (count-weighted, like the fleet's global cache view)."""
    merged: dict[str, dict] = {}
    for m in metrics_list:
        for t, v in m.tenant_cache_summary().items():
            agg = merged.setdefault(t, {"hits": 0, "misses": 0})
            agg["hits"] += v["hits"]
            agg["misses"] += v["misses"]
    for agg in merged.values():
        total = agg["hits"] + agg["misses"]
        agg["hit_rate"] = round(agg["hits"] / total, 4) if total else None
    return merged


def scenario_16(size: str = "tiny", replicas: int = 2) -> dict:
    """Traffic-observatory smoke (torchkafka_tpu/workload + obs/burn): a
    seeded Zipf 3-tenant Poisson burst storm — heavy-tailed prompt-
    suffix and output lengths, mixed QoS lanes, keyed partition pinning
    — driven on a ManualClock through a 2-replica traced fleet with the
    paged cache + chunked prefill on, a burn-rate monitor evaluating a
    TTFT SLO per round, and per-record output budgets enforced via the
    ``max_new`` header. Prints the per-tenant goodput / burn-rate report
    production watches; the tier-1 guard asserts non-degenerate
    per-tenant SLOs, trace balance, and zero lost records. The same-seed
    byte-identity differential lives in tests/test_workload.py and the
    overload sweep in benchmarks/bench_traffic.py."""
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import QoSConfig, ServingFleet
    from torchkafka_tpu.obs import SLOTarget
    from torchkafka_tpu.resilience import ManualClock
    from torchkafka_tpu.source.records import TopicPartition
    from torchkafka_tpu.workload import WorkloadConfig, WorkloadGenerator
    from torchkafka_tpu.workload.generator import header_max_new

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 24 if size == "tiny" else 128
    block = 4 if size == "tiny" else 16
    parts = 4
    slots = 2  # small pool: the burst storm provably queues
    tick_dt = 0.002
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    wcfg = WorkloadConfig(
        tenants=3, zipf_s=1.2, total_records=n,
        arrival_rate=1500.0, burst_mean=4.0,  # a storm: well over service
        interactive_fraction=0.4,
        mean_suffix=max(4.0, prompt_len / 3),
        mean_output=max_new * 0.75,
        seed=16,
    )
    gen = WorkloadGenerator(
        wcfg, prompt_len=prompt_len, max_new=max_new,
        vocab_size=cfg.vocab_size,
    )
    mc = ManualClock()
    broker = tk.InMemoryBroker()
    broker.create_topic("t16", partitions=parts)
    pages = {
        "block_size": block,
        "num_blocks": slots * -(-(prompt_len + max_new) // block) + 16,
    }
    targets = [SLOTarget(
        metric="ttft", threshold_s=tick_dt * 12, objective=0.75,
        fast_window_s=tick_dt * 32, slow_window_s=tick_dt * 128,
        min_samples=4,
    )]
    fleet = ServingFleet(
        gen.consumer_factory(broker, "t16", "s16", clock=mc),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=max_new, slots=slots, qos=QoSConfig(), commit_every=4,
        clock=mc.now,
        gen_kwargs={"kv_pages": pages, "max_new_of": header_max_new},
        obs=True, slo_targets=targets,
    )
    fleet.warmup()
    t0 = _time.perf_counter()
    drive = gen.drive(fleet, broker, "t16", clock=mc, tick_dt=tick_dt)
    elapsed = _time.perf_counter() - t0
    served_keys = set(drive["served_keys"])
    produced = {
        (p, o) for p in range(parts)
        for o in range(broker.end_offset(TopicPartition("t16", p)))
    }
    committed_complete = all(
        broker.committed("s16", TopicPartition("t16", p))
        == broker.end_offset(TopicPartition("t16", p))
        for p in {p for p, _ in produced}  # keyed: only pinned partitions
    )
    s = fleet.metrics.summary(fleet.replicas)
    slo = s["slo"]
    mon = fleet.monitor.summary()

    def pct(leaf):
        return {
            "count": leaf["count"],
            "p50_ms": round(leaf["p50_ms"], 3),
            "p99_ms": round(leaf["p99_ms"], 3),
        }

    zero = {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0}
    tenant_slo = {
        t: {
            "ttft": pct(slo["ttft"]["by_tenant"].get(t, zero)),
            "itl": pct(slo["itl"]["by_tenant"].get(t, zero)),
        }
        for t in gen.tenant_names
    }
    out_lens = sorted(
        {len(np.asarray(t)) for _rid, _r, t in drive["completions"]}
    )
    trace_summary = fleet.tracer.summary()
    fleet.close()
    fleet.tracer.close()
    return {
        "scenario": "16:traffic-observatory",
        "model_scale": label,
        "replicas": replicas,
        "records": drive["unique_served"],
        "elapsed_s": round(elapsed, 3),
        "records_per_s": (
            round(drive["unique_served"] / elapsed, 1) if elapsed else None
        ),
        "schedule_digest": gen.schedule_digest()[:16],
        "tenant_arrivals": gen.tenant_counts(),
        "all_arrived": drive["all_arrived"],
        "coverage_complete": served_keys == produced,
        "committed_complete": committed_complete,
        "duplicates": drive["duplicates"],
        "synthetic_span_s": round(drive["end_time_s"], 3),
        "tenant_slo": tenant_slo,
        "ttft": pct(slo["ttft"]["all"]),
        "itl": pct(slo["itl"]["all"]),
        "queue_wait": pct(slo["queue_wait"]["all"]),
        "e2e": pct(slo["e2e"]["all"]),
        "lanes_observed": sorted(slo["ttft"]["by_lane"]),
        "goodput": s["goodput"],
        "burn_states": mon["states"],
        "burn_transitions": mon["transitions"],
        "burn_evaluations": mon["evaluations"],
        "overload_deferrals": sum(
            v["deferred"] for v in s["goodput"]["tenants"].values()
        ),
        "output_len_spread": out_lens,
        "output_capped": s["serving"]["output_capped"],
        "step_time": {
            "ticks": s["serving"]["ticks"],
            "p50_ms": round(s["serving"]["step_time"]["p50_ms"], 3),
            "p99_ms": round(s["serving"]["step_time"]["p99_ms"], 3),
        },
        "cache_hit_rate": s["prefix_cache"]["hit_rate"],
        "tenant_cache": _merge_tenant_cache(
            [rep.gen.metrics for rep in fleet.replicas]
        ),
        "trace_events": trace_summary["events"],
        "trace_stages": trace_summary["stages"],
        "open_records_end": trace_summary["open_records"],
        "dropped": sum(
            rep.gen.metrics.dropped.count for rep in fleet.replicas
        ),
        "commit_failures": sum(
            rep.gen.metrics.commit_failures.count for rep in fleet.replicas
        ),
    }


def scenario_17(size: str = "tiny", replicas: int = 2) -> dict:
    """Process-fleet kill storm (torchkafka_tpu/fleet/supervisor): R
    REAL OS-process replicas over the socket broker — each with its own
    BrokerClient, its own jit state, its own on-disk decode journal —
    under heartbeat leases; one replica is SIGKILLed mid-storm while it
    provably holds uncommitted served work. The supervisor fences the
    corpse, the rebalance re-delivers its partitions, and the survivor
    loads the victim's journal FROM DISK across the process boundary to
    resume warm. Audited: zero lost records (committed watermark covers
    every prompt after drain), every completion — duplicates included —
    BYTE-IDENTICAL to an in-process no-kill reference, duplicates within
    the fleet-wide uncommitted-work bound, the victim's journal provably
    handed off, and a post-mortem commit forged from the victim's stale
    generation REJECTED with the watermark unmoved. The full matrix
    (crash points, SIGSTOP zombies, elastic scale) lives in
    tests/test_procfleet.py and tests/test_crash_matrix.py."""
    import tempfile
    import time as _time

    import jax

    import torchkafka_tpu as tk
    from torchkafka_tpu.errors import CommitFailedError
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.models.transformer import init_params
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 12 if size == "tiny" else 48
    parts, slots, commit_every = 4, 2, 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(17)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)

    # In-process no-kill reference: greedy decode is a pure function of
    # (params, prompt), so one local server defines byte-truth for every
    # process in the fleet.
    rb = tk.InMemoryBroker()
    rb.create_topic("t17", partitions=parts)
    for i in range(n):
        rb.produce("t17", prompts[i].tobytes(), partition=i % parts,
                   key=str(i).encode())
    rc = tk.MemoryConsumer(rb, "t17", group_id="ref17")
    ref_gen = StreamingGenerator(
        rc, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=commit_every, ticks_per_sync=1,
    )
    ref = {rec.key: toks for rec, toks in ref_gen.run(idle_timeout_ms=400)}
    rc.close()

    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        fleet = ProcessFleet(
            model_spec, topic="t17", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=3.0, heartbeat_interval_s=0.2,
            journal_cadence=1, respawn=False, group="s17",
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            # Produce AFTER every member joined: the storm hits a settled
            # 2-way partition split, not whichever process won the warmup
            # race.
            for i in range(n):
                fleet.broker.produce(
                    "t17", prompts[i].tobytes(), partition=i % parts,
                    key=str(i).encode(),
                )

            def key_offset(key: bytes) -> tuple[int, int]:
                i = int(key.decode())
                return i % parts, i // parts

            def uncommitted_output_of(member: str) -> bool:
                wm = {
                    p: fleet.broker.committed(
                        "s17", TopicPartition("t17", p)
                    ) or 0
                    for p in range(parts)
                }
                for key, copies in fleet.results().items():
                    p, off = key_offset(key)
                    if off >= wm[p] and any(m == member for m, _ in copies):
                        return True
                return False

            # SIGKILL a replica the moment it provably holds SERVED,
            # UNCOMMITTED work (an output past the watermark): the death
            # then must exercise redelivery AND the journal handoff.
            victim = None
            deadline = _time.monotonic() + 240
            while victim is None:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "no kill opportunity arose\n" + fleet.diagnose()
                    )
                done = len(fleet.results()) >= n
                for inc in fleet.live():
                    if done:
                        break
                    if uncommitted_output_of(inc.member):
                        victim = fleet.kill_replica(inc.idx)
                        break
                if done and victim is None:
                    raise RuntimeError(
                        "storm finished before any replica held "
                        "uncommitted served work — shrink commit_every"
                    )
                _time.sleep(0.01)

            # Survivors absorb (instant supervisor fencing on the reaped
            # corpse; the lease is the fallback), then drain commits all.
            fleet.wait(
                lambda f: set(f.results())
                == {str(i).encode() for i in range(n)},
                timeout_s=240,
            )
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=120,
            )
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            res = fleet.results()
            duplicates = sum(len(v) - 1 for v in res.values())
            # Every member's uncommitted work re-delivers at the eager
            # rebalance (the victim's AND the survivors'), so the bound
            # is fleet-wide.
            dup_bound = replicas * (commit_every + slots)
            identical = set(res) == set(ref) and all(
                np.array_equal(toks, ref[k])
                for k, copies in res.items() for _m, toks in copies
            )

            # The zombie-fencing acceptance: a post-mortem commit from
            # the killed member's stale generation bounces, watermark
            # unmoved.
            wm_before = {
                p: fleet.broker.committed("s17", TopicPartition("t17", p))
                for p in range(parts)
            }
            try:
                fleet.broker.commit(
                    "s17", {TopicPartition("t17", 0): 1},
                    member_id=victim["member"],
                    generation=victim["generation"],
                )
                zombie_rejected = False
            except CommitFailedError:
                zombie_rejected = True
            wm_after = {
                p: fleet.broker.committed("s17", TopicPartition("t17", p))
                for p in range(parts)
            }
            vic_inc = [
                i for i in fleet.incarnations
                if i.member == victim["member"]
            ][0]
            worker_m = fleet.worker_metrics()
            warm_used = sum(
                m["warm_resumes"] + m["served_from_journal"]
                for m in worker_m
            )
            membership = fleet.broker.membership("s17")
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "17:process-fleet-kill-storm",
        "model_scale": label,
        "replicas": replicas,
        "records": n,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "victim": victim["member"],
        "victim_sigkilled": vic_inc.exit_code == -9,
        "fence_reason": vic_inc.fence_reason,
        "fence_count": membership["fence_count"],
        "zero_lost": zero_lost,
        "identical_to_no_kill": identical,
        "duplicates": duplicates,
        "duplicate_bound": dup_bound,
        "duplicates_within_bound": duplicates <= dup_bound,
        "journal_handoff_entries": vic_inc.handoff_entries,
        "warm_resumes_plus_journal_served": warm_used,
        "zombie_commit_rejected": zombie_rejected,
        "watermark_unmoved_by_zombie": wm_before == wm_after,
        "exit_codes": {
            i.member: (None if i.proc is None else i.proc.returncode)
            for i in fleet.incarnations
        },
    }


def scenario_18(size: str = "tiny", replicas: int = 2) -> dict:
    """Exactly-once under SIGKILL: the scenario-17 kill storm upgraded
    to transactional output (``ProcessFleet(exactly_once=True)``). Each
    replica process serves through a ``TransactionalProducer`` whose
    transactional id is keyed by replica INDEX — one transaction per
    commit window covering that window's completions AND offsets. One
    replica is SIGKILLed while it provably holds outputs in an OPEN
    (uncommitted) transaction; the supervisor fences it, bumping the
    producer epoch, which ABORTS the in-flight transaction — so a
    ``read_committed`` consumer of the output topic observes ZERO
    duplicates and zero losses (asserted equal, not bounded), every
    committed completion byte-identical to the no-kill reference. A
    commit forged from the victim's stale epoch raises
    ``ProducerFencedError`` with the watermark and committed view
    untouched. The at-least-once duplicates are still VISIBLE in the
    read_uncommitted view (the aborted copies hold their offsets) —
    exactly Kafka's shape, reported for contrast."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.errors import ProducerFencedError
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 10 if size == "tiny" else 48
    parts, slots, commit_every = 2, 2, 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(18)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)

    # In-process no-kill reference (greedy decode is a pure function of
    # (params, prompt)).
    rb = tk.InMemoryBroker()
    rb.create_topic("t18", partitions=parts)
    for i in range(n):
        rb.produce("t18", prompts[i].tobytes(), partition=i % parts,
                   key=str(i).encode())
    rc = tk.MemoryConsumer(rb, "t18", group_id="ref18")
    ref_gen = StreamingGenerator(
        rc, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=commit_every, ticks_per_sync=1,
    )
    ref = {rec.key: toks for rec, toks in ref_gen.run(idle_timeout_ms=400)}
    rc.close()

    all_keys = {str(i).encode() for i in range(n)}
    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        fleet = ProcessFleet(
            model_spec, topic="t18", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=3.0, heartbeat_interval_s=0.2,
            journal_cadence=1, respawn=False, group="s18",
            exactly_once=True,
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            for i in range(n):
                fleet.broker.produce(
                    "t18", prompts[i].tobytes(), partition=i % parts,
                    key=str(i).encode(),
                )

            from torchkafka_tpu.journal import DecodeJournal

            def uncommitted_served_work(inc) -> bool:
                """True when the incarnation's on-disk journal holds a
                FINISHED completion whose offset the committed watermark
                has not passed: served work whose output has NOT reached
                a committed transaction (in exactly-once mode staged
                outputs are invisible until their transaction commits,
                so the journal — pruned at every commit — is the
                outside-observable evidence). Killing here forces the
                abort + journal-handoff + re-serve-exactly-once path."""
                try:
                    entries = DecodeJournal.load(inc.journal_path)
                except Exception:
                    return False
                for (topic, p, off), e in entries.items():
                    if not e.finished or topic != "t18":
                        continue
                    wm = fleet.broker.committed(
                        "s18", TopicPartition("t18", p)
                    ) or 0
                    if off >= wm:
                        return True
                return False

            victim = None
            deadline = _time.monotonic() + 240
            while victim is None:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "no kill opportunity arose\n" + fleet.diagnose()
                    )
                done = len(fleet.results("read_committed")) >= n
                for inc in fleet.live():
                    if done:
                        break
                    if uncommitted_served_work(inc):
                        victim = fleet.kill_replica(inc.idx)
                        break
                if done and victim is None:
                    raise RuntimeError(
                        "storm finished before any replica held "
                        "uncommitted served work — shrink commit_every"
                    )
                _time.sleep(0.01)

            def covered(f) -> bool:
                """Every prompt either already in the committed view or
                FINISHED in a live member's journal (staged in its
                outbox — the drain flush will commit it). Unlike
                scenario 17's raw-coverage wait, nothing of the victim's
                aborted work counts: only work that can still reach the
                committed view."""
                committed = set(f.results("read_committed"))
                if committed >= all_keys:
                    return True
                pending = set()
                for inc in f.live():
                    try:
                        entries = DecodeJournal.load(inc.journal_path)
                    except Exception:
                        continue
                    for (topic, p, off), e in entries.items():
                        if e.finished and topic == "t18":
                            pending.add(str(off * parts + p).encode())
                return committed | pending >= all_keys

            fleet.wait(covered, timeout_s=240)
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=120,
            )
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            committed_res = fleet.results("read_committed")
            uncommitted_res = fleet.results()
            # THE exactly-once assertion: the committed view holds each
            # completion EXACTLY once — zero duplicates, not a bound.
            committed_dups = sum(
                len(v) - 1 for v in committed_res.values()
            )
            raw_dups = sum(len(v) - 1 for v in uncommitted_res.values())
            aborted_copies = (
                sum(len(v) for v in uncommitted_res.values())
                - sum(len(v) for v in committed_res.values())
            )
            identical = set(committed_res) == set(ref) and all(
                np.array_equal(toks, ref[k])
                for k, copies in committed_res.items()
                for _m, toks in copies
            )

            # The epoch-fencing acceptance: a commit forged from the
            # victim's stale epoch bounces, watermark + committed view
            # untouched. (The supervisor's fence already bumped the
            # victim's transactional id to a newer epoch.)
            txn_id = f"s18-r{victim['idx']:03d}"
            pid, cur_epoch = fleet.broker.init_producer_id(txn_id)
            wm_before = {
                p: fleet.broker.committed("s18", TopicPartition("t18", p))
                for p in range(parts)
            }
            try:
                fleet.broker.commit_txn(pid, cur_epoch - 1)
                zombie_rejected = False
            except ProducerFencedError:
                zombie_rejected = True
            wm_after = {
                p: fleet.broker.committed("s18", TopicPartition("t18", p))
                for p in range(parts)
            }
            committed_after_forgery = fleet.results("read_committed")
            vic_inc = [
                i for i in fleet.incarnations
                if i.member == victim["member"]
            ][0]
            worker_m = fleet.worker_metrics()
            warm_used = sum(
                m["warm_resumes"] + m["served_from_journal"]
                for m in worker_m
            )
            membership = fleet.broker.membership("s18")
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "18:exactly-once-kill-storm",
        "model_scale": label,
        "replicas": replicas,
        "records": n,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "victim": victim["member"],
        "victim_sigkilled": vic_inc.exit_code == -9,
        "fence_count": membership["fence_count"],
        "zero_lost": zero_lost,
        "identical_to_no_kill": identical,
        "committed_duplicates": committed_dups,
        "read_uncommitted_duplicates": raw_dups,
        "aborted_copies_in_log": aborted_copies,
        "journal_handoff_entries": vic_inc.handoff_entries,
        "warm_resumes_plus_journal_served": warm_used,
        "zombie_txn_commit_rejected": zombie_rejected,
        "watermark_unmoved_by_zombie": wm_before == wm_after,
        "committed_view_unmoved_by_zombie": (
            {k: len(v) for k, v in committed_after_forgery.items()}
            == {k: len(v) for k, v in committed_res.items()}
        ),
        "exit_codes": {
            i.member: (None if i.proc is None else i.proc.returncode)
            for i in fleet.incarnations
        },
    }


def scenario_19(size: str = "tiny", replicas: int = 2) -> dict:
    """Broker death mid-storm: the last unfenced process joins the fault
    model. A 2-process ``exactly_once`` fleet serves over a DURABLE
    broker (``ProcessFleet(wal_dir=...)`` — every produce/commit/
    membership/transaction event write-ahead logged, source/wal.py);
    once a worker's journal proves served-but-uncommitted work exists,
    the broker is killed UNCLEANLY (``restart_broker(crash=True)``: the
    listener and every connection drop mid-RPC, the in-memory state is
    abandoned un-flushed) and held down long enough that the workers'
    circuit breakers OPEN. The supervisor then recovers a fresh broker
    from the WAL on the SAME port: records, offsets, generations,
    producer epochs, and memberships (fresh leases) come back; open
    transactions abort. Workers ride the outage on the reconnect stack
    (RetryPolicy → BrokerUnavailableError → CircuitBreaker) and resume
    — no fencing, no respawn. Audited: zero lost records, committed-view
    duplicates EXACTLY zero, every committed completion byte-identical
    to a no-restart reference, and every worker's breaker opened during
    the outage then closed after recovery (the open-then-close
    transition counters in the worker metrics dumps)."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.journal import DecodeJournal
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 12 if size == "tiny" else 48
    parts, slots, commit_every = 4, 2, 4
    down_s = 2.5
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(19)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    all_keys = {str(i).encode() for i in range(n)}

    # In-process no-restart reference (greedy decode is a pure function
    # of (params, prompt)).
    rb = tk.InMemoryBroker()
    rb.create_topic("t19", partitions=parts)
    for i in range(n):
        rb.produce("t19", prompts[i].tobytes(), partition=i % parts,
                   key=str(i).encode())
    rc = tk.MemoryConsumer(rb, "t19", group_id="ref19")
    ref_gen = StreamingGenerator(
        rc, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=commit_every, ticks_per_sync=1,
    )
    ref = {rec.key: toks for rec, toks in ref_gen.run(idle_timeout_ms=400)}
    rc.close()

    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        import os as _os

        fleet = ProcessFleet(
            model_spec, topic="t19", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=8.0, heartbeat_interval_s=0.2,
            journal_cadence=1, respawn=False, group="s19",
            exactly_once=True,
            wal_dir=_os.path.join(td, "wal"), wal_durability="batch",
            # Short client retries so the outage is FELT (and ridden)
            # by the resilience stack instead of silently absorbed
            # inside the transport: the breakers must provably open.
            resilient=True, reconnect_attempts=2,
            reconnect_deadline_s=0.4,
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            for i in range(n):
                fleet.broker.produce(
                    "t19", prompts[i].tobytes(), partition=i % parts,
                    key=str(i).encode(),
                )

            def uncommitted_served_work(inc) -> bool:
                """Scenario 18's kill criterion, re-aimed at the broker:
                a FINISHED journal entry past the committed watermark
                proves in-flight transactional work exists for the crash
                to strand."""
                try:
                    entries = DecodeJournal.load(inc.journal_path)
                except Exception:  # noqa: BLE001 - mid-write race
                    return False
                for (topic, p, off), e in entries.items():
                    if not e.finished or topic != "t19":
                        continue
                    wm = fleet.broker.committed(
                        "s19", TopicPartition("t19", p)
                    ) or 0
                    if off >= wm:
                        return True
                return False

            deadline = _time.monotonic() + 240
            while not any(
                uncommitted_served_work(i) for i in fleet.live()
            ):
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "no crash opportunity arose\n" + fleet.diagnose()
                    )
                if len(fleet.results("read_committed")) >= n:
                    raise RuntimeError(
                        "storm finished before any worker held "
                        "uncommitted served work — shrink commit_every"
                    )
                _time.sleep(0.01)

            recovery = fleet.restart_broker(crash=True, down_s=down_s)

            def covered(f) -> bool:
                committed = set(f.results("read_committed"))
                if committed >= all_keys:
                    return True
                pending = set()
                for inc in f.live():
                    try:
                        entries = DecodeJournal.load(inc.journal_path)
                    except Exception:  # noqa: BLE001 - mid-write race
                        continue
                    for (topic, p, off), e in entries.items():
                        if e.finished and topic == "t19":
                            pending.add(str(off * parts + p).encode())
                return committed | pending >= all_keys

            fleet.wait(covered, timeout_s=240)
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=120,
            )
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            committed_res = fleet.results("read_committed")
            committed_dups = sum(
                len(v) - 1 for v in committed_res.values()
            )
            identical = set(committed_res) == set(ref) and all(
                np.array_equal(toks, ref[k])
                for k, copies in committed_res.items()
                for _m, toks in copies
            )
            worker_m = fleet.worker_metrics()
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "19:broker-crash-recovery-storm",
        "model_scale": label,
        "replicas": replicas,
        "records": n,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "broker_down_s": down_s,
        "broker_restarts": fleet.metrics.broker_restarts.count,
        "recovery": recovery,
        "zero_lost": zero_lost,
        "identical_to_no_restart": identical,
        "committed_duplicates": committed_dups,
        "workers_survived_unfenced": all(
            m["exit"] == 0 for m in worker_m
        ) and len(worker_m) == replicas,
        "breaker_opens": {
            m["member"]: m["circuit_opens"] for m in worker_m
        },
        "breaker_closes": {
            m["member"]: m["circuit_closes"] for m in worker_m
        },
        "heartbeat_outages": sum(
            m["heartbeat_outages"] for m in worker_m
        ),
        "exit_codes": {
            i.member: (None if i.proc is None else i.proc.returncode)
            for i in fleet.incarnations
        },
    }


def scenario_20(size: str = "tiny", replicas: int = 2) -> dict:
    """Sharded paged serving smoke (PR 13, ROADMAP item 1): a 2-replica
    in-process fleet whose generators compose the four KV-backend axes
    at once — PAGED block tables + radix prefix reuse, INT8 payloads,
    the Pallas read under its ``auto`` probe, and a {data, tp}
    host-device MESH (kv heads + weights over tp; the paged per-slot
    state rides replicated, serve.py ``pin_paged``). Three keyed
    tenants with fixed system prompts (the scenario-12 shape) so the
    radix tree does real work while sharded. The tier-1 guard for the
    composed path: coverage + commit exactness and a non-degenerate
    cache hit rate (token-exactness vs single-device serving is
    tests/test_kvcache.py's sharded differential; the wall-clock story
    is benchmarks/bench_kvcache.py --mesh)."""
    import time as _time

    import jax

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ServingFleet
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 24 if size == "tiny" else 128
    block = 4 if size == "tiny" else 16
    sys_len = 3 * block
    parts = 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    n_dev = len(jax.devices())
    tp = 2 if n_dev >= 2 and cfg.n_kv_heads % 2 == 0 else 1
    data = 2 if n_dev >= 2 * tp else 1
    mesh = tk.make_mesh(
        {"data": data, "tp": tp}, devices=jax.devices()[: data * tp]
    )
    broker = tk.InMemoryBroker()
    broker.create_topic("t20", partitions=parts)
    rng = np.random.default_rng(0)
    tenants = ("alpha", "beta", "gamma")
    system = {
        t: rng.integers(0, cfg.vocab_size, sys_len, dtype=np.int32)
        for t in tenants
    }
    produced = []
    for i in range(n):
        t = tenants[i % len(tenants)]
        prompt = np.concatenate([
            system[t],
            rng.integers(0, cfg.vocab_size, prompt_len - sys_len,
                         dtype=np.int32),
        ])
        rec = broker.produce("t20", prompt.tobytes(), key=t.encode())
        produced.append((rec.partition, rec.offset))
    # 2 slots/replica: the auto chunk width follows slots × prompt_len,
    # and the fused program's compile time follows the chunk width —
    # the tier-1 smoke budget lever (coverage is unchanged; admissions
    # just wave through in more quanta).
    slots = 2 if size == "tiny" else 4
    pages = {
        "block_size": block,
        "num_blocks": slots * -(-(prompt_len + max_new) // block) + 16,
    }
    fleet = ServingFleet(
        lambda rid: tk.MemoryConsumer(broker, "t20", group_id="s20"),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=max_new, slots=slots, commit_every=4,
        gen_kwargs={
            "kv_pages": pages, "kv_dtype": "int8", "kv_kernel": "auto",
            "mesh": mesh,
        },
    )
    fleet.warmup()
    t0 = _time.perf_counter()
    served = fleet.serve_all(idle_timeout_ms=2000)
    elapsed = _time.perf_counter() - t0
    keys = {(r.partition, r.offset) for _rid, r, _t in served}
    committed_complete = all(
        broker.committed("s20", TopicPartition("t20", rec_p))
        == broker.end_offset(TopicPartition("t20", rec_p))
        for rec_p in {p for p, _ in produced}
    )
    s = fleet.metrics.summary(fleet.replicas)
    cache = s["prefix_cache"]
    gens = [rep.gen for rep in fleet.replicas]
    kv_backend = gens[0].metrics.summary()["kv_backend"]
    fleet.close()
    return {
        "scenario": "20:sharded-paged-int8-fleet",
        "model_scale": label,
        "replicas": replicas,
        "mesh": {"data": data, "tp": tp},
        "kv_backend": kv_backend,
        "records": len(served),
        "elapsed_s": round(elapsed, 3),
        "records_per_s": round(len(served) / elapsed, 1) if elapsed else None,
        "coverage_complete": keys == set(produced),
        "committed_complete": committed_complete,
        "tenants": len(tenants),
        "system_prompt_tokens": sys_len,
        "cache": cache,
        "prefill_tokens": cache["prefill_tokens"],
        "prefill_tokens_dense": n * prompt_len,
        "prefill_savings_pct": round(
            100 * (1 - cache["prefill_tokens"] / (n * prompt_len)), 1
        ),
        "commit_failures": sum(
            g.metrics.commit_failures.count for g in gens
        ),
        "dropped": sum(g.metrics.dropped.count for g in gens),
    }


def scenario_21(size: str = "tiny", replicas: int = 2) -> dict:
    """Disaggregated serving under prefill-worker death (fleet/prefill):
    1 PREFILL worker + R decode replicas as REAL OS processes over the
    socket broker — the prefill worker consumes the prompt topic in its
    own group, fills paged KV, and publishes handoffs; decode replicas
    route admission through the handoff shelf and ADOPT (no prompt pass
    on the decode path). Mid-storm the prefill worker is SIGKILLed:
    unpublished handoffs vanish, the decode replicas' routing patience
    expires and they fall back to local prefills — the optimization
    degrades, correctness does not. Audited: zero lost records, every
    completion byte-identical to an in-process monolithic paged
    reference, adoptions provably happened before the kill, decode tick
    time never stalled (p99 reported from worker metric dumps), and the
    prefill group's offsets never covered an unpublished handoff."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 24 if size == "tiny" else 64  # 4x oversubscription of 2x2 slots
    parts, slots, commit_every = 4, 2, 4
    pages = {"block_size": 4, "num_blocks": 64}
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(21)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    prompts[:, :4] = np.arange(4)  # shared system prefix (radix shape)

    # In-process monolithic paged reference: byte-truth for the fleet.
    rb = tk.InMemoryBroker()
    rb.create_topic("t21", partitions=parts)
    for i in range(n):
        rb.produce("t21", prompts[i].tobytes(), partition=i % parts,
                   key=str(i).encode())
    rc = tk.MemoryConsumer(rb, "t21", group_id="ref21")
    ref_gen = StreamingGenerator(
        rc, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=commit_every, ticks_per_sync=1,
        kv_pages=dict(pages),
    )
    ref = {rec.key: toks for rec, toks in ref_gen.run(idle_timeout_ms=400)}
    rc.close()

    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        fleet = ProcessFleet(
            model_spec, topic="t21", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=5.0, heartbeat_interval_s=0.2,
            journal_cadence=2, respawn=False, group="s21",
            kv_pages=pages, prefill_replicas=1, route_patience=1500,
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            for i in range(n):
                fleet.broker.produce(
                    "t21", prompts[i].tobytes(), partition=i % parts,
                    key=str(i).encode(),
                )
            ho_tp = TopicPartition(fleet.handoff_topic, 0)

            # SIGKILL the prefill worker MID-storm: after some handoffs
            # are provably on the transfer plane, before all are.
            deadline = _time.monotonic() + 240
            while True:
                published = fleet.broker.end_offset(ho_tp)
                if published >= 6:
                    break
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "no handoffs ever published\n" + fleet.diagnose()
                    )
                _time.sleep(0.005)
            victim = fleet.kill_prefill(0)
            published_at_kill = fleet.broker.end_offset(ho_tp)

            fleet.wait(
                lambda f: set(f.results())
                == {str(i).encode() for i in range(n)},
                timeout_s=240,
            )
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=120,
            )
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            res = fleet.results()
            duplicates = sum(len(v) - 1 for v in res.values())
            identical = set(res) == set(ref) and all(
                np.array_equal(toks, ref[k])
                for k, copies in res.items() for _m, toks in copies
            )
            # The prefill group never committed past its published
            # handoffs (the mid-transfer at-least-once contract).
            published_keys = {
                r.key for r in fleet.broker.fetch(ho_tp, 0, 100000)
            }
            prefill_wm_ok = True
            for p in range(parts):
                tp = TopicPartition("t21", p)
                wm = fleet.broker.committed("s21-prefill", tp) or 0
                for off in range(wm):
                    if str(off * parts + p).encode() not in published_keys:
                        prefill_wm_ok = False
            decode_m = [
                m for m in fleet.worker_metrics()
                if m.get("role") != "prefill"
            ]
            adopted = sum(m.get("adopted_slots", 0) for m in decode_m)
            routed = sum(m.get("prefill_routed", 0) for m in decode_m)
            fallback_tokens = sum(
                m.get("prefill_tokens", 0) for m in decode_m
            )
            step_p99 = max(
                (m.get("step_p99_ms") or 0.0) for m in decode_m
            ) if decode_m else None
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "21:disaggregated-prefill-kill-storm",
        "model_scale": label,
        "decode_replicas": replicas,
        "prefill_workers": 1,
        "records": n,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "victim": victim["member"],
        "handoffs_published_at_kill": int(published_at_kill),
        "zero_lost": zero_lost,
        "identical_to_monolithic": identical,
        "duplicates": duplicates,
        "adopted_slots": adopted,
        "prefill_routed": routed,
        "decode_fallback_prefill_tokens": fallback_tokens,
        "decode_step_p99_ms": step_p99,
        "prefill_watermark_never_past_published": prefill_wm_ok,
    }


def scenario_22(size: str = "tiny", replicas: int = 1) -> dict:
    """Closed-loop autoscaling under a step-load storm (fleet/autoscale,
    ROADMAP item 2): a ManualClock in-process fleet starts at
    ``replicas`` decode members with the burn-rate + queue-depth
    controller ON; the workload steps to 6× offered load mid-run and
    back. Asserted shape: the controller scales UP under the step
    (hysteresis bounding the decision count under Poisson burst noise),
    the SLO RECOVERS under the added capacity (burn state back to ok,
    with the recovery instant on record), capacity is handed back warm
    AFTER the step ends (scale-down decisions strictly later than
    t_off; drains commit — zero lost), and the WHOLE control loop —
    arrivals, burn transitions, controller decisions, scale events,
    completions, ledger — replays byte-identically at the same seed
    (the scenario runs twice and compares)."""
    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import (
        AutoscaleController, FleetAutoscaler, QoSConfig, RolePolicy,
        ServingFleet,
    )
    from torchkafka_tpu.obs import SLOTarget
    from torchkafka_tpu.resilience import ManualClock
    from torchkafka_tpu.source.records import TopicPartition
    from torchkafka_tpu.workload import (
        WorkloadConfig, WorkloadGenerator, header_max_new, step_load,
    )

    prompt_len, max_new = (16, 8) if size == "tiny" else (64, 32)
    n = 32 if size == "tiny" else 96
    parts, slots, commit_every = 4, 2, 4
    tick_dt = 0.002
    t_on, t_off, factor = 0.04, 0.14, 6.0
    max_replicas = 3
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)

    def run_once():
        import time as _time

        wcfg = WorkloadConfig(
            tenants=3, zipf_s=1.2, total_records=n, arrival_rate=260.0,
            burst_mean=3.0, interactive_fraction=0.5,
            mean_suffix=max(4.0, prompt_len / 3),
            mean_output=max_new * 0.75, seed=22,
            rate_schedule=step_load(t_on, factor, t_off),
        )
        gen = WorkloadGenerator(
            wcfg, prompt_len=prompt_len, max_new=max_new,
            vocab_size=cfg.vocab_size,
        )
        mc = ManualClock()
        broker = tk.InMemoryBroker()
        broker.create_topic("t22", partitions=parts)
        pages = {
            "block_size": 4,
            "num_blocks": slots * -(-(prompt_len + max_new) // 4) + 16,
        }
        targets = [SLOTarget(
            metric="ttft", threshold_s=tick_dt * 12, objective=0.75,
            fast_window_s=tick_dt * 32, slow_window_s=tick_dt * 128,
            min_samples=4,
        )]
        fleet = ServingFleet(
            gen.consumer_factory(broker, "t22", "s22", clock=mc),
            params, cfg, replicas=replicas, prompt_len=prompt_len,
            max_new=max_new, slots=slots, commit_every=commit_every,
            clock=mc.now, qos=QoSConfig(),
            gen_kwargs={"kv_pages": pages, "max_new_of": header_max_new},
            obs=True, slo_targets=targets,
        )
        ctrl = AutoscaleController({
            "decode": RolePolicy(
                min_replicas=replicas, max_replicas=max_replicas,
                queue_high=4.0, queue_low=1.0,
                up_cooldown_s=tick_dt * 8, down_cooldown_s=tick_dt * 24,
                down_confirm=6,
            ),
        }, clock=mc.now, tracer=fleet.tracer, metrics=fleet.metrics)
        scaler = FleetAutoscaler(fleet, ctrl)
        peak = {"live": replicas}

        def on_round(f, _served):
            scaler.step()
            peak["live"] = max(peak["live"], f.live_count())

        fleet.warmup()
        t0 = _time.perf_counter()
        report = gen.drive(
            fleet, broker, "t22", clock=mc, tick_dt=tick_dt,
            settle_rounds=200, on_round=on_round,
        )
        wall_s = _time.perf_counter() - t0
        order = [
            (rid, rec.partition, rec.offset,
             tuple(np.asarray(t).tolist()))
            for rid, rec, t in report["completions"]
        ]
        committed = {
            p: broker.committed("s22", TopicPartition("t22", p)) or 0
            for p in range(parts)
        }
        produced = {
            (p, o) for p in range(parts)
            for o in range(broker.end_offset(TopicPartition("t22", p)))
        }
        # Burn recovery instant: the last transition back to "ok" on
        # the global ttft scope, read off the typed event stream.
        burn_ok_t = None
        for e in fleet.tracer.events:
            if e.stage == "burn_state":
                attrs = dict(e.attrs)
                if attrs["dim"] == "" and attrs["to"] == "ok":
                    burn_ok_t = e.t
        out = {
            "order": order,
            "events": list(fleet.tracer.events),
            "committed": committed,
            "produced": produced,
            "report": report,
            "decisions": list(ctrl.decisions),
            "digest": ctrl.decision_digest(),
            "ctrl": ctrl.summary(),
            "goodput": fleet.monitor.goodput_summary(),
            "end_burn": fleet.monitor.worst_state(),
            "burn_ok_t": burn_ok_t,
            "transitions": fleet.monitor.transitions,
            "drains": fleet.metrics.drains.count,
            "peak_live": peak["live"],
            "wall_s": wall_s,
        }
        fleet.close()
        fleet.tracer.close()
        return out

    a = run_once()
    b = run_once()
    replay_identical = (
        a["order"] == b["order"]
        and a["events"] == b["events"]
        and a["committed"] == b["committed"]
        and a["digest"] == b["digest"]
    )
    served = {(p, o) for _rid, p, o, _t in a["order"]}
    ups = [d for d in a["decisions"] if d.direction == "up"]
    downs = [d for d in a["decisions"] if d.direction == "down"]
    g = a["goodput"]
    return {
        "scenario": "22:autoscaled-step-storm",
        "model_scale": label,
        "records": n,
        "step": {"t_on": t_on, "t_off": t_off, "factor": factor},
        "replay_identical": replay_identical,
        "zero_lost": served == a["produced"] and a["report"]["all_arrived"],
        "duplicates": a["report"]["duplicates"],
        "peak_live": a["peak_live"],
        "scale_ups": len(ups),
        "scale_downs": len(downs),
        "decisions": a["ctrl"]["decisions"],
        "by_reason": a["ctrl"]["by_reason"],
        "first_up_t": round(ups[0].t_s, 4) if ups else None,
        "first_down_t": round(downs[0].t_s, 4) if downs else None,
        "downs_after_step_end": all(d.t_s > t_off for d in downs),
        "final_target": a["ctrl"]["targets"]["decode"],
        "burn_transitions": a["transitions"],
        "burn_recovered_t": (
            round(a["burn_ok_t"], 4) if a["burn_ok_t"] is not None
            else None
        ),
        "end_burn_state": a["end_burn"],
        "drained_members": a["drains"],
        "goodput_ratio": g["goodput_ratio"],
        "within_slo": g["within_slo"],
        "completed": g["completed"],
        "wall_s": round(a["wall_s"] + b["wall_s"], 2),
    }


def scenario_8(size: str = "tiny") -> dict:
    """Streaming CTR: DLRM-style recommender trained from a Kafka event
    stream — label + dense features + hashed categorical ids per record,
    row-sharded embedding tables over tp, commit-after-step. The canonical
    production consumer of the reference's ingest loop (no reference
    analog: it ships no model code)."""
    import jax
    import jax.numpy as jnp
    import optax

    import torchkafka_tpu as tk
    from torchkafka_tpu.models.recsys import (
        DLRMConfig, count_params, make_chunk_processor, make_dlrm_train_step,
        record_nbytes,
    )

    n_dev = len(jax.devices())
    tp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    mesh = tk.make_mesh({"data": n_dev // tp, "tp": tp})
    cfg = (
        DLRMConfig(dense_dim=4, vocab_sizes=(64, 32, 128), embed_dim=8,
                   bottom_mlp=(16, 8), top_mlp=(32, 1))
        if size == "tiny"
        else DLRMConfig()  # 8 tables x 100k rows x 64 — tables are the bytes
    )
    steps = 24 if size == "tiny" else 40
    local_batch = 4 * n_dev if size == "tiny" else 4096
    n = steps * local_batch

    broker = tk.InMemoryBroker()
    parts = max(n_dev, 4)
    broker.create_topic("ctr", partitions=parts)
    rng = np.random.default_rng(0)

    def _records():
        for _ in range(n):
            dense = rng.normal(size=cfg.dense_dim).astype(np.float32)
            cats = np.array(
                [rng.integers(0, v) for v in cfg.vocab_sizes], np.int32
            )
            label = np.float32(dense.sum() > 0)
            yield label.tobytes() + dense.tobytes() + cats.tobytes()

    broker.produce_many("ctr", _records())
    consumer = tk.MemoryConsumer(
        broker, "ctr", group_id="s8",
        assignment=tk.partitions_for_process("ctr", parts, 0, 1),
    )
    init_fn, step_fn = make_dlrm_train_step(cfg, mesh, optax.adam(1e-2))
    params, opt_state = init_fn(jax.random.key(0))
    state = {"params": params, "opt": opt_state, "losses": []}

    def step(batch):
        mask = jnp.asarray(batch.valid_mask(), jnp.float32)
        state["params"], state["opt"], loss = step_fn(
            state["params"], state["opt"], batch.data["dense"],
            batch.data["cats"], batch.data["label"], mask,
        )
        state["losses"].append(loss)
        return loss

    with tk.KafkaStream(
        consumer, make_chunk_processor(cfg), batch_size=local_batch,
        mesh=mesh, idle_timeout_ms=2000, owns_consumer=True,
    ) as stream:
        rows, elapsed = _drain(stream, step, n)
    losses = [float(x) for x in state["losses"]]
    q = max(1, len(losses) // 4)

    # Ingest-vs-step decomposition (VERDICT r2): an end-to-end number that
    # can't state its split can't guide optimization. (a) PURE train step:
    # the fori-chained device slope. (b) PURE ingest: re-read the same
    # broker under a fresh group with no device step.
    dense0 = jnp.zeros((local_batch, cfg.dense_dim), jnp.float32)
    cats0 = jnp.zeros((local_batch, len(cfg.vocab_sizes)), jnp.int32)
    label0 = jnp.zeros((local_batch,), jnp.float32)
    mask0 = jnp.ones((local_batch,), jnp.float32)
    # Pure device step via the shared fori-chained slope (see _train_mfu's
    # docstring for why Python-loop chains measure dispatch, not device).
    from torchkafka_tpu.utils.timing import device_step_seconds

    step_s, step_slope_ok = device_step_seconds(
        step_fn, state["params"], state["opt"], dense0, cats0, label0, mask0
    )
    c2 = tk.MemoryConsumer(
        broker, "ctr", group_id="s8-ingest",
        assignment=tk.partitions_for_process("ctr", parts, 0, 1),
    )
    with tk.KafkaStream(
        c2, make_chunk_processor(cfg), batch_size=local_batch,
        mesh=mesh, idle_timeout_ms=2000, owns_consumer=True,
    ) as s2:
        rows2, elapsed2 = _drain(s2, None, n)
    ingest_rps = rows2 / elapsed2 if elapsed2 else 0.0

    # Paired ingest-only ratio vs the torch-user analog (per-record struct
    # parse through the compat DataLoader path), host-only on both sides.
    import torch

    k_cats = len(cfg.vocab_sizes)

    def ours_slice(group_id: str, n_s: int):
        c = tk.MemoryConsumer(
            broker, "ctr", group_id=group_id,
            assignment=tk.partitions_for_process("ctr", parts, 0, 1),
        )
        with tk.KafkaStream(
            c, make_chunk_processor(cfg), batch_size=local_batch,
            to_device=False, idle_timeout_ms=2000, owns_consumer=True,
        ) as s:
            return _drain(s, None, n_s)

    def ref_process(rec):
        v = rec.value
        d = 4 + 4 * cfg.dense_dim
        return (
            torch.from_numpy(np.frombuffer(v[:4], np.float32).copy()),
            torch.from_numpy(np.frombuffer(v[4:d], np.float32).copy()),
            torch.from_numpy(np.frombuffer(v[d : d + 4 * k_cats], np.int32).copy()),
        )

    paired = _paired_host_ratio(
        broker, "ctr", parts, ours_slice, ref_process, local_batch,
        (n // 2) // local_batch * local_batch,
    )
    return _result(
        "8:streaming-ctr", rows, elapsed, stream,
        {
            "mesh": dict(mesh.shape),
            "record_bytes": record_nbytes(cfg),
            "params_m": round(count_params(state["params"]) / 1e6, 1),
            # Degenerate slope (transport drift) → flag, never publish the
            # floored value (two_point_slope's contract).
            "step_slope_ok": step_slope_ok,
            "step_ms_pure": round(step_s * 1e3, 2) if step_slope_ok else None,
            "ingest_only_rows_per_s": round(ingest_rps, 1),
            **paired,
            "step_share_pct": round(
                100 * (steps * step_s) / elapsed, 1
            ) if (elapsed and step_slope_ok) else None,
            "first_loss": round(losses[0], 4),
            "last_loss": round(losses[-1], 4),
            # Every step sees a FRESH batch (true streaming), so single-step
            # losses are noisy; head/tail quartile means are the trend.
            "head_loss_mean": round(float(np.mean(losses[:q])), 4),
            "tail_loss_mean": round(float(np.mean(losses[-q:])), 4),
        },
    )


def scenario_9(size: str = "tiny") -> dict:
    """Ragged text topic → length-bucketed batches → per-width train steps,
    commit-after-step. Demonstrates the static-shape answer to variable-
    length streams (SURVEY §7 hard part (a)): one cached XLA compile per
    bucket width instead of padding every record to the maximum.

    PAIRED (VERDICT r4 weak #6): the same records replay pad-to-max in the
    SAME invocation (every row padded to the top width, same model, same
    step), so ``vs_padmax`` is a MEASURED end-to-end ratio under the same
    box conditions — not the self-referential ``bucket_efficiency`` token
    count (still reported: it is the analytic ceiling the measured ratio
    should approach as steps dominate)."""
    import jax
    import jax.numpy as jnp
    import optax

    import torchkafka_tpu as tk
    from torchkafka_tpu.models import TransformerConfig, make_train_step

    n_dev = len(jax.devices())
    mesh = tk.make_mesh({"data": n_dev})
    buckets = (16, 32, 64) if size == "tiny" else (64, 128, 256, 512)
    max_w = buckets[-1]
    cfg = (
        TransformerConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=128, max_seq_len=max_w,
                          dtype=jnp.float32)
        if size == "tiny"
        else TransformerConfig(max_seq_len=max_w)
    )
    n = 256 if size == "tiny" else 6144
    local_batch = 2 * n_dev if size == "tiny" else 8 * n_dev

    broker = tk.InMemoryBroker()
    parts = max(n_dev, 4)
    broker.create_topic("t9", partitions=parts)
    rng = np.random.default_rng(0)
    # Zipf-ish length mix: mostly short, a long tail — the shape that makes
    # pad-to-max wasteful and bucketing worthwhile.
    lengths = np.minimum(
        (rng.pareto(1.2, n) * 0.15 * max_w + 5).astype(int), max_w
    )
    broker.produce_many(
        "t9",
        (
            rng.integers(0, cfg.vocab_size, k).astype(np.int32).tobytes()
            for k in lengths
        ),
    )
    init_fn, step_fn = make_train_step(cfg, mesh, optax.adamw(1e-3))

    def padmax_processor(rec):
        row = np.frombuffer(rec.value, np.int32)
        out = np.zeros(max_w, np.int32)
        out[: row.shape[0]] = row
        return {"tokens": out, "length": np.int32(row.shape[0])}

    def run_pass(tag: str, bucketed: bool):
        """One full stream+train pass over the SAME topic (fresh group —
        re-reads from offset 0). Shared step_fn: the pad-to-max pass
        reuses the bucketed pass's top-width XLA compile and vice versa,
        so neither side pays compilation the other did not."""
        consumer = tk.MemoryConsumer(
            broker, "t9", group_id=f"s9-{tag}",
            assignment=tk.partitions_for_process("t9", parts, 0, 1),
        )
        params, opt_state = init_fn(jax.random.key(0))
        state = {"p": params, "o": opt_state, "losses": []}
        rows_by_width: dict[int, int] = {}
        batches_by_width: dict[int, int] = {}

        def step(batch):
            toks = jnp.asarray(batch.data["tokens"])
            w = toks.shape[1]
            rows_by_width[w] = rows_by_width.get(w, 0) + batch.valid_count
            batches_by_width[w] = batches_by_width.get(w, 0) + 1
            # Mask: real rows AND real (pre-pad) positions within each row.
            ln = np.asarray(batch.data["length"])
            mask = (
                np.arange(w)[None, :] < ln[:, None]
            ) & batch.valid_mask()[:, None]
            state["p"], state["o"], loss = step_fn(
                state["p"], state["o"], toks,
                jnp.asarray(mask.astype(np.int32)),
            )
            state["losses"].append(loss)
            return loss

        processor = (
            (lambda rec: np.frombuffer(rec.value, np.int32))
            if bucketed else padmax_processor
        )
        with tk.KafkaStream(
            consumer,
            processor,
            batch_size=local_batch,
            pad_policy="pad",
            mesh=mesh,
            idle_timeout_ms=2000,
            owns_consumer=True,
            **({"buckets": buckets} if bucketed else {}),
        ) as stream:
            rows, elapsed = _drain(stream, step, n)
        losses = [float(x) for x in state["losses"]]
        return rows, elapsed, losses, rows_by_width, batches_by_width, stream

    # Warmup pass (untimed-in-the-ratio; first-contact compiles land here),
    # then bucketed and pad-to-max back-to-back — both sides sample the
    # same minutes of box weather, bench.py's pairing discipline.
    run_pass("warm", bucketed=True)
    rows, elapsed, losses, rows_by_width, batches_by_width, stream = run_pass(
        "bucketed", bucketed=True
    )
    p_rows, p_elapsed, p_losses, _pw, p_batches, _ = run_pass(
        "padmax", bucketed=False
    )
    assert p_rows == rows, (p_rows, rows)
    bucketed_tokens = sum(w * r for w, r in rows_by_width.items())
    extra = {
        "mesh": dict(mesh.shape),
        "buckets": list(buckets),
        "rows_per_width": {
            int(w): int(r) for w, r in sorted(rows_by_width.items())
        },
        "bucket_efficiency": round(bucketed_tokens / (rows * max_w), 3),
        # MEASURED same-invocation ratio: pad-to-max elapsed over
        # bucketed elapsed on identical records and model (>1 =
        # bucketing wins end-to-end). On dispatch-bound transports (this
        # tunnel: both sides run ~the same batch count through ~100 ms
        # round trips) this reads ≈1 regardless of the device saving —
        # the device-level ratio below is the number that transfers.
        "vs_padmax": round(p_elapsed / elapsed, 2) if elapsed else None,
        "padmax_records_per_s": (
            round(p_rows / p_elapsed, 1) if p_elapsed else None
        ),
        "first_loss": round(losses[0], 4),
        "last_loss": round(losses[-1], 4),
        "padmax_last_loss": round(p_losses[-1], 4),
    }
    if jax.default_backend() == "tpu":
        # DEVICE-level paired step cost: fori-chained slope per width
        # (utils.timing.device_step_seconds — one dispatch per window, the
        # only timing that converges on this transport), weighted by the
        # batch counts the bucketed pass ACTUALLY ran vs every batch at
        # the top width. This is the measured train-step ratio the
        # analytic bucket_efficiency predicts.
        from torchkafka_tpu.utils.timing import device_step_seconds

        dp, do = init_fn(jax.random.key(1))
        rng2 = np.random.default_rng(5)
        step_s: dict[int, float] = {}
        # TWO rounds per width, keep the min of the rounds whose SLOPE
        # HELD: the first measurement after the e2e passes absorbs
        # queue-drain/cache cold-start (observed: a width-64 step reading
        # 10.3 ms while width-128 read 4.3 in the same run), min-of-rounds
        # is the standard de-noise for step walls on a drifting chip, and
        # a degenerate round (ok=False → floored 1e-9) must be DISCARDED,
        # not min'd in — two_point_slope's contract is flag-don't-publish.
        for _ in range(2):
            for w in buckets:
                toks = jnp.asarray(
                    rng2.integers(0, cfg.vocab_size, (local_batch, w)),
                    jnp.int32,
                )
                msk = jnp.ones((local_batch, w), jnp.int32)
                s, ok = device_step_seconds(step_fn, dp, do, toks, msk)
                if ok:
                    step_s[w] = min(step_s.get(w, float("inf")), s)
        slopes_ok = len(step_s) == len(buckets)
        extra.update({
            "device_step_ms_per_width": {
                int(w): round(s * 1e3, 2) for w, s in sorted(step_s.items())
            },
            "batches_per_width": {
                int(w): int(b) for w, b in sorted(batches_by_width.items())
            },
            "device_slopes_ok": slopes_ok,
        })
        if slopes_ok:
            bucketed_dev = sum(
                step_s[w] * b for w, b in batches_by_width.items()
            )
            # The padmax side's own batch count (bucket fragmentation
            # gives the bucketed pass a couple more part-full batches).
            padmax_dev = step_s[max_w] * sum(p_batches.values())
            extra.update({
                "bucketed_device_step_s": round(bucketed_dev, 2),
                "padmax_device_step_s": round(padmax_dev, 2),
                "vs_padmax_device": (
                    round(padmax_dev / bucketed_dev, 2)
                    if bucketed_dev else None
                ),
            })
        else:
            # No valid slope for some width in either round: publishing a
            # ratio built on floored values would fabricate the headline.
            extra.update({
                "bucketed_device_step_s": None,
                "padmax_device_step_s": None,
                "vs_padmax_device": None,
            })
    return _result("9:ragged-bucketed-train", rows, elapsed, stream, extra)


def scenario_23(size: str = "tiny", replicas: int = 2) -> dict:
    """Quorum-cell leader death mid-storm (ISSUE 17): the broker itself
    becomes highly available. A 2-process ``exactly_once`` fleet serves
    over a 3-REPLICA broker cell (``ProcessFleet(broker_replicas=3,
    wal_durability="quorum")`` — every acked mutation majority-held
    across WAL replicas before the client hears back). Once a worker's
    journal proves served-but-uncommitted transactional work exists, the
    LEADER is dropped the way SIGKILL would drop it (listener gone
    mid-conversation, WAL abandoned un-flushed) and the cell runs its
    epoch-bumped election: the longest-prefix follower replays through
    PR-11 recovery (dangling transactions aborted, LSO recomputed) and
    takes over the SAME advertised port. Workers reconnect through their
    retry stacks, unfenced — promotion, not restart, so there is no
    ride-through window to hold open. Audited: zero lost records,
    committed-view duplicates EXACTLY zero, every committed completion
    byte-identical to a no-kill reference, and the deposed leader's
    forged late append REJECTED by the bumped epoch
    (``StaleEpochError``) — the cell-level twin of scenario 18's fenced
    zombie commit."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.errors import StaleEpochError
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.journal import DecodeJournal
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    n = 12 if size == "tiny" else 48
    parts, slots, commit_every = 4, 2, 4
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(23)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    all_keys = {str(i).encode() for i in range(n)}

    # In-process no-kill reference (greedy decode is a pure function of
    # (params, prompt)).
    rb = tk.InMemoryBroker()
    rb.create_topic("t23", partitions=parts)
    for i in range(n):
        rb.produce("t23", prompts[i].tobytes(), partition=i % parts,
                   key=str(i).encode())
    rc = tk.MemoryConsumer(rb, "t23", group_id="ref23")
    ref_gen = StreamingGenerator(
        rc, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=commit_every, ticks_per_sync=1,
    )
    ref = {rec.key: toks for rec, toks in ref_gen.run(idle_timeout_ms=400)}
    rc.close()

    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        import os as _os

        fleet = ProcessFleet(
            model_spec, topic="t23", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=8.0, heartbeat_interval_s=0.2,
            journal_cadence=1, respawn=False, group="s23",
            exactly_once=True,
            wal_dir=_os.path.join(td, "cell"), wal_durability="quorum",
            broker_replicas=3,
            # Short client retries so the failover gap is FELT by the
            # resilience stack (and provably ridden), not absorbed.
            resilient=True, reconnect_attempts=2,
            reconnect_deadline_s=0.4,
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            for i in range(n):
                fleet.broker.produce(
                    "t23", prompts[i].tobytes(), partition=i % parts,
                    key=str(i).encode(),
                )

            def uncommitted_served_work(inc) -> bool:
                """Scenario 19's kill criterion, re-aimed at the leader:
                a FINISHED journal entry past the committed watermark
                proves in-flight transactional work exists for the
                election to strand — the committed view must not move."""
                try:
                    entries = DecodeJournal.load(inc.journal_path)
                except Exception:  # noqa: BLE001 - mid-write race
                    return False
                for (topic, p, off), e in entries.items():
                    if not e.finished or topic != "t23":
                        continue
                    wm = fleet.broker.committed(
                        "s23", TopicPartition("t23", p)
                    ) or 0
                    if off >= wm:
                        return True
                return False

            deadline = _time.monotonic() + 240
            while not any(
                uncommitted_served_work(i) for i in fleet.live()
            ):
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "no kill opportunity arose\n" + fleet.diagnose()
                    )
                if len(fleet.results("read_committed")) >= n:
                    raise RuntimeError(
                        "storm finished before any worker held "
                        "uncommitted served work — shrink commit_every"
                    )
                _time.sleep(0.01)

            failover = fleet.kill_leader()

            # The deposed leader's late write: a forged frame carrying
            # the OLD epoch must be rejected by every follower, never
            # applied — zombie fencing at the cell level.
            forged_rejected = False
            try:
                fleet._cell.forge_deposed_frame()
            except StaleEpochError:
                forged_rejected = True

            def covered(f) -> bool:
                committed = set(f.results("read_committed"))
                if committed >= all_keys:
                    return True
                pending = set()
                for inc in f.live():
                    try:
                        entries = DecodeJournal.load(inc.journal_path)
                    except Exception:  # noqa: BLE001 - mid-write race
                        continue
                    for (topic, p, off), e in entries.items():
                        if e.finished and topic == "t23":
                            pending.add(str(off * parts + p).encode())
                return committed | pending >= all_keys

            fleet.wait(covered, timeout_s=240)
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=120,
            )
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            committed_res = fleet.results("read_committed")
            committed_dups = sum(
                len(v) - 1 for v in committed_res.values()
            )
            identical = set(committed_res) == set(ref) and all(
                np.array_equal(toks, ref[k])
                for k, copies in committed_res.items()
                for _m, toks in copies
            )
            cell_status = fleet._cell.status()
            worker_m = fleet.worker_metrics()
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "23:quorum-leader-failover-storm",
        "model_scale": label,
        "replicas": replicas,
        "broker_replicas": 3,
        "records": n,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "leader_elections": fleet.metrics.leader_elections.count,
        "failover": {
            "victim_idx": failover["victim_idx"],
            "winner_idx": failover["winner_idx"],
            "old_epoch": failover["old_epoch"],
            "epoch": failover["epoch"],
            "candidates": failover["candidates"],
            "election_ms": round(failover["election_ms"], 2),
            "failover_ms": round(failover["failover_ms"], 2),
            "recovery": failover["recovery"],
        },
        "cell_epoch": cell_status["epoch"],
        "zero_lost": zero_lost,
        "identical_to_no_kill": identical,
        "committed_duplicates": committed_dups,
        "deposed_append_rejected": forged_rejected,
        "workers_survived_unfenced": all(
            m["exit"] == 0 for m in worker_m
        ) and len(worker_m) == replicas,
        "exit_codes": {
            i.member: (None if i.proc is None else i.proc.returncode)
            for i in fleet.incarnations
        },
    }


def scenario_24(size: str = "tiny", replicas: int = 2) -> dict:
    """Rolling weight hot-swap with canary auto-rollback (ISSUE 18): the
    model itself becomes a live, versioned resource. A 2-process
    ``exactly_once`` fleet serves a storm while the supervisor drives
    TWO rollouts over the broker control plane. First a DIVERGENT v1
    (different weights) is published to the checkpoint topic and rolled
    out: the canary replica shadow-serves a deterministic slice under
    v1, token-diffs against its own live incumbent output, and the
    controller AUTOMATICALLY rolls back on divergence — no replica ever
    serves v1 into the committed view. Then a CLEAN v2 (byte-identical
    weights, new version) rolls out to completion: canary passes,
    replicas drain-swap one at a time (quiesce → close the commit
    window → journal the version → rebind, zero recompile), and the
    fleet's incumbent advances. Audited: zero lost records,
    committed-view duplicates EXACTLY zero, every committed completion
    byte-identical to a no-rollout reference, and every output's "mv"
    version tag ∈ {0, 2} — the divergent version left no trace."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ProcessFleet
    from torchkafka_tpu.fleet.proc import build_model
    from torchkafka_tpu.journal import DecodeJournal
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    prompt_len, max_new = (8, 16) if size == "tiny" else (32, 32)
    parts, slots, commit_every = 4, 2, 4
    pool = 400  # prompt pool upper bound; the storm produces on demand
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    model_spec = dict(
        seed=0, vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq_len=cfg.max_seq_len,
    )
    rng = np.random.default_rng(24)
    prompts = rng.integers(0, cfg.vocab_size, (pool, prompt_len),
                           dtype=np.int32)

    t0 = _time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        fleet = ProcessFleet(
            model_spec, topic="t24", prompt_len=prompt_len,
            max_new=max_new, workdir=td, replicas=replicas,
            partitions=parts, slots=slots, commit_every=commit_every,
            session_timeout_s=8.0, heartbeat_interval_s=0.2,
            journal_cadence=1, respawn=False, group="s24",
            out_topic="out24", exactly_once=True, rollout=True,
            rollout_topic="roll24", ckpt_topic="ckpt24",
            idle_exit_ms=None,
        )
        nkeys = 0

        def produce(n: int) -> None:
            nonlocal nkeys
            for _ in range(n):
                if nkeys >= pool:
                    raise RuntimeError("prompt pool exhausted")
                fleet.broker.produce(
                    "t24", prompts[nkeys].tobytes(),
                    partition=nkeys % parts, key=str(nkeys).encode(),
                )
                nkeys += 1

        def feed() -> None:
            """Keep the storm alive WITHOUT flooding: the canary needs
            live completions to compare, but an unthrottled producer
            outruns tiny-model decode and bloats the reference replay —
            top the uncommitted backlog back up to a small constant."""
            backlog = nkeys - len(fleet.results("read_committed"))
            if backlog < 12:
                produce(2)

        try:
            fleet.start()
            fleet.wait_ready(timeout_s=300)
            ready_s = _time.perf_counter() - t0
            produce(8)

            # --- rollout 1: DIVERGENT weights → canary auto-rollback --
            _, divergent = build_model(dict(model_spec, seed=1))
            fleet.publish_checkpoint(1, divergent)
            drv1 = fleet.start_rollout(1, canary_slice=3)
            deadline = _time.monotonic() + 180
            while not fleet.rollout_done:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "divergent rollout never resolved\n"
                        + fleet.diagnose()
                    )
                fleet.poll_once()
                feed()  # the canary compares LIVE traffic
                _time.sleep(0.05)
            phase1 = drv1.controller.phase
            reason1 = drv1.controller.rollback_reason
            versions1 = dict(drv1.controller.member_versions)
            rollback_s = _time.perf_counter() - t0 - ready_s

            # --- rollout 2: CLEAN weights (same bytes, new version) →
            # canary passes, every replica drain-swaps, incumbent
            # advances ---------------------------------------------------
            _, clean = build_model(model_spec)
            fleet.publish_checkpoint(2, clean)
            drv2 = fleet.start_rollout(2, canary_slice=3)
            deadline = _time.monotonic() + 180
            while not fleet.rollout_done:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        "clean rollout never completed\n" + fleet.diagnose()
                    )
                fleet.poll_once()
                feed()
                _time.sleep(0.05)
            phase2 = drv2.controller.phase
            versions2 = dict(drv2.controller.member_versions)
            fleet_version = fleet.model_version

            # Serve out the tail BEFORE draining: drain abandons
            # queued-but-unadmitted records (loss-free by re-delivery,
            # but this fleet is about to exit for good), so wait until
            # every produced key is either committed or finished in a
            # live worker's journal — then the drain only has to flush.
            tail_keys = {str(i).encode() for i in range(nkeys)}

            def covered(f) -> bool:
                done = set(f.results("read_committed"))
                if done >= tail_keys:
                    return True
                for inc in f.live():
                    try:
                        entries = DecodeJournal.load(inc.journal_path)
                    except Exception:  # noqa: BLE001 - mid-write race
                        continue
                    for (topic, p, off), e in entries.items():
                        if e.finished and topic == "t24":
                            done.add(str(off * parts + p).encode())
                return done >= tail_keys

            fleet.wait(covered, timeout_s=240)
            fleet.drain()
            fleet.wait(lambda f: not f.live(), timeout_s=120)
            fleet.poll_once()
            zero_lost = fleet.fully_committed()

            committed_res = fleet.results(isolation="read_committed")
            committed_dups = sum(
                len(v) - 1 for v in committed_res.values()
            )
            all_keys = {str(i).encode() for i in range(nkeys)}
            none_lost = set(committed_res) == all_keys

            # Version tags on the committed view: the divergent v1 must
            # have left NO committed trace; everything is v0 or v2.
            tags: dict = {}
            for p in range(fleet.broker.partitions_for("out24")):
                recs, _ = fleet.broker.fetch_stable(
                    TopicPartition("out24", p), 0, 10**6,
                )
                for rec in recs:
                    mv = dict(rec.headers or ()).get("mv", b"?")
                    tags[mv.decode()] = tags.get(mv.decode(), 0) + 1
            divergent_leaked = "1" in tags
            tags_consistent = set(tags) <= {"0", "2"}

            # No-rollout byte-truth: v2's weights ARE v0's, so one
            # seed-0 greedy reference covers every committed output
            # regardless of which side of the swap served it.
            rb = tk.InMemoryBroker()
            rb.create_topic("r24", partitions=parts)
            for i in range(nkeys):
                rb.produce("r24", prompts[i].tobytes(),
                           partition=i % parts, key=str(i).encode())
            rcons = tk.MemoryConsumer(rb, "r24", group_id="ref24")
            ref_gen = StreamingGenerator(
                rcons, params, cfg, slots=slots, prompt_len=prompt_len,
                max_new=max_new, commit_every=commit_every,
                ticks_per_sync=1,
            )
            ref = {
                rec.key: toks
                for rec, toks in ref_gen.run(idle_timeout_ms=400)
            }
            rcons.close()
            identical = all(
                np.array_equal(toks, ref[k])
                for k, copies in committed_res.items()
                for _m, toks in copies
            )
            worker_m = fleet.worker_metrics()
            elapsed = _time.perf_counter() - t0
        finally:
            fleet.close()
    return {
        "scenario": "24:rolling-hot-swap-canary-rollback",
        "model_scale": label,
        "replicas": replicas,
        "records": nkeys,
        "ready_s": round(ready_s, 2),
        "elapsed_s": round(elapsed, 2),
        "divergent_rollout": {
            "phase": phase1,
            "rollback_reason": reason1,
            "member_versions": versions1,
            "resolved_s": round(rollback_s, 2),
        },
        "clean_rollout": {
            "phase": phase2,
            "member_versions": versions2,
        },
        "fleet_model_version": fleet_version,
        "version_tags": tags,
        "divergent_version_leaked": divergent_leaked,
        "version_tags_consistent": tags_consistent,
        "zero_lost": bool(zero_lost and none_lost),
        "identical_to_no_rollout": identical,
        "committed_duplicates": committed_dups,
        "workers_survived": all(m["exit"] == 0 for m in worker_m)
        and len(worker_m) == replicas,
    }


def scenario_25(size: str = "tiny", replicas: int = 2) -> dict:
    """Online draft distillation, the loop closed (ISSUE 19): a
    speculative serving fleet TEACHES ITS OWN DRAFT from live traffic
    and rides out a traffic drift. A 2-replica in-process spec fleet
    serves a Zipf workload whose hot set ROTATES mid-run
    (``hot_set_rotation`` — the rank→tenant remap moves which shared
    context prefixes dominate, i.e. real prompt-content drift). Decode
    replicas stage committed (prompt, tokens) completions onto the
    distill topic inside their commit windows; a DistillTrainer pumped
    on the same scheduling rounds trains the layer-truncated draft on
    that corpus and publishes versioned checkpoints; the fleet's
    DistillController (ManualClock hysteresis) auto-refreshes every
    replica's draft via ``swap_draft_params`` between ticks — no
    quiesce. Measured per phase: α with the distilled draft on
    stationary traffic RISES above the untrained-truncation baseline,
    DEGRADES at the drift instant (the distilled draft specialised to
    the old hot set), and RECOVERS after the post-drift refresh
    (α_post > α_drift — the closed loop's whole point). Audited:
    committed tokens BYTE-IDENTICAL to a never-distilled reference
    fleet on the same workload seed (a draft refresh changes only the
    proposer; the target's verification commits), zero duplicates."""
    import tempfile
    import time as _time

    import torchkafka_tpu as tk
    from torchkafka_tpu.distill import DistillPolicy, DistillTrainer
    from torchkafka_tpu.fleet import ServingFleet
    from torchkafka_tpu.resilience import ManualClock
    from torchkafka_tpu.serve_spec import SpecStreamingGenerator
    from torchkafka_tpu.source.producer import MemoryProducer
    from torchkafka_tpu.workload import WorkloadConfig, WorkloadGenerator

    prompt_len, max_new = (8, 16) if size == "tiny" else (16, 32)
    total = 240 if size == "tiny" else 480
    t_drift = 0.45  # synthetic seconds; ~half the schedule
    cfg, params, label = _serving_model(size, None, prompt_len, max_new)
    wl_cfg = WorkloadConfig(
        # Steep Zipf (rank-1 ≈ 70% of traffic) + near-pure context
        # prompts: maximally learnable pre-drift, maximally WRONG after
        # the rotation — the crispest α signal the loop can get.
        tenants=6, zipf_s=2.0, total_records=total, arrival_rate=230.0,
        burst_mean=2.0, interactive_fraction=1.0, mean_suffix=1.5,
        seed=25,
        # Shift 3 of 6: every popularity rank lands on a different
        # tenant, so the post-drift hot set shares NO context prefix
        # with what the draft distilled on.
        hot_set_rotation=((t_drift, 3),),
    )

    def run(distill: bool) -> dict:
        wl = WorkloadGenerator(
            wl_cfg, prompt_len=prompt_len, max_new=max_new,
            vocab_size=cfg.vocab_size,
        )
        broker = tk.InMemoryBroker()
        broker.create_topic("t25", partitions=4)
        broker.create_topic("d25", partitions=1)
        broker.create_topic("ck25", partitions=1)
        clock = ManualClock()
        gen_kwargs = dict(
            k=3, draft_layers=1, ticks_per_sync=4,
            distill_topic="d25",
            distill_producer=MemoryProducer(broker),
        )
        fleet = ServingFleet(
            wl.consumer_factory(broker, "t25", "s25", resilient=False),
            params, cfg, replicas=replicas, prompt_len=prompt_len,
            max_new=max_new, slots=4, commit_every=4,
            generator_cls=SpecStreamingGenerator, gen_kwargs=gen_kwargs,
            clock=clock.now, obs=True,
        )
        trainer = None
        driver = None
        refreshes: list[tuple[float, int]] = []  # (t_s, version)
        rounds: list[tuple[float, int, int]] = []  # (t_s, acc, prop)
        if distill:
            tcons = tk.MemoryConsumer(broker, "d25", group_id="tr25")
            trainer = DistillTrainer(
                tcons, params, cfg, seq_len=prompt_len + max_new,
                batch_size=8, draft_layers=1, learning_rate=5e-3,
                broker=broker, ckpt_topic="ck25", publish_every=6,
                metrics=fleet.metrics,
            )
            driver = fleet.start_distill(
                policy=DistillPolicy(
                    window_rounds=24, min_proposed=32,
                    # Track the trainer: every published version rolls
                    # once the SYNTHETIC-clock cooldown allows — sized
                    # so refreshes land a few times per phase.
                    cooldown_s=0.10, refresh_on_publish=True,
                ),
                broker=broker, ckpt_topic="ck25",
            )

        def hook(f, served):
            if trainer is not None:
                # Pump the trainer a bounded chunk per scheduling round
                # (the in-process twin of the distill worker's chunked
                # loop), then push any fresh versions at the controller.
                trainer.run(max_steps=2, idle_timeout_ms=1)
                driver.note_version(trainer.published)
                driver.on_round(f, served)
            acc = prop = 0
            for rep in f.replicas:
                if rep.runnable:
                    st = rep.gen.spec_stats()
                    acc += st["accepted"]
                    prop += st["proposed"]
            rounds.append((clock.now(), acc, prop))
            if driver is not None and driver.controller.refreshes > len(
                refreshes
            ):
                refreshes.append(
                    (clock.now(), driver.controller.applied_version)
                )

        try:
            res = wl.drive(
                fleet, broker, "t25", clock=clock, tick_dt=0.002,
                idle_timeout_ms=4000, on_round=hook, settle_rounds=60,
            )
        finally:
            fleet.close()
            if trainer is not None:
                tcons.close()
        committed = {
            (rec.partition, rec.offset): np.asarray(toks).tobytes()
            for _rid, rec, toks in res["completions"]
        }
        return {
            "res": res, "committed": committed, "rounds": rounds,
            "refreshes": refreshes,
            "trainer": trainer.report() if trainer else None,
            "controller": {
                "refreshes": driver.controller.refreshes,
                "applied_version": driver.controller.applied_version,
                "alpha_window": driver.controller.alpha_window,
            } if driver else None,
            "metrics": fleet.metrics.summary(),
        }

    def alpha_between(rounds, t0, t1) -> tuple[float | None, int]:
        """α over rounds with t0 <= t < t1, from cumulative counters."""
        inside = [(a, p) for t, a, p in rounds if t0 <= t < t1]
        if len(inside) < 2:
            return None, 0
        d_acc = inside[-1][0] - inside[0][0]
        d_prop = inside[-1][1] - inside[0][1]
        return (
            (d_acc / d_prop if d_prop else None), d_prop,
        )

    t0 = _time.perf_counter()
    live = run(distill=True)
    ref = run(distill=False)
    elapsed = _time.perf_counter() - t0

    refreshes = live["refreshes"]
    pre = [t for t, _v in refreshes if t < t_drift]
    # The RECOVERY refresh: the first applied once the trainer has had
    # a grace window to consume post-drift corpus. Refreshes landing
    # within the grace carry mostly pre-drift gradients — they belong
    # to the degraded phase, not the recovery.
    grace = 0.10
    post = [t for t, _v in refreshes if t >= t_drift + grace]
    end = live["rounds"][-1][0]
    t_rec = post[0] if post else end
    # Phase α from the recorded cumulative counters: distilled-
    # stationary (the LATE pre-drift window — the draft at its most
    # specialised), drifted-stale (drift → recovery refresh), and
    # recovered (recovery refresh → end).
    alpha_pre, n_pre = alpha_between(
        live["rounds"], max(t_drift - 0.2, pre[0] if pre else 0.0),
        t_drift,
    )
    alpha_drift, n_drift = alpha_between(live["rounds"], t_drift, t_rec)
    alpha_post, n_post = alpha_between(live["rounds"], t_rec, end + 1.0)
    # The committed-view differential: byte-identical tokens at every
    # (partition, offset) the two runs share — and both served all.
    same_keys = set(live["committed"]) == set(ref["committed"])
    identical = same_keys and all(
        live["committed"][k] == ref["committed"][k]
        for k in live["committed"]
    )
    return {
        "scenario": "25:online-draft-distillation",
        "model_scale": label,
        "replicas": replicas,
        "records": total,
        "elapsed_s": round(elapsed, 2),
        "drift_t_s": t_drift,
        "refreshes": [(round(t, 4), v) for t, v in refreshes],
        "refreshes_pre_drift": len(pre),
        "refreshes_post_drift": len(post),
        "trainer": live["trainer"],
        "alpha_pre": round(alpha_pre, 4) if alpha_pre is not None else None,
        "alpha_drift": (
            round(alpha_drift, 4) if alpha_drift is not None else None
        ),
        "alpha_post": (
            round(alpha_post, 4) if alpha_post is not None else None
        ),
        "alpha_windows_proposed": [n_pre, n_drift, n_post],
        "alpha_degraded_at_drift": (
            alpha_pre is not None and alpha_drift is not None
            and alpha_drift < alpha_pre
        ),
        "alpha_recovered": (
            alpha_drift is not None and alpha_post is not None
            and alpha_post > alpha_drift
        ),
        "identical_to_no_distill": identical,
        "committed_duplicates": live["res"]["duplicates"],
        "all_arrived": live["res"]["all_arrived"]
        and ref["res"]["all_arrived"],
        "distill_metrics": live["metrics"].get("distill"),
    }


SCENARIOS = {
    1: scenario_1,
    2: scenario_2,
    3: scenario_3,
    4: scenario_4,
    5: scenario_5,
    6: scenario_6,
    7: scenario_7,
    8: scenario_8,
    9: scenario_9,
    10: scenario_10,
    11: scenario_11,
    12: scenario_12,
    13: scenario_13,
    14: scenario_14,
    15: scenario_15,
    16: scenario_16,
    17: scenario_17,
    18: scenario_18,
    19: scenario_19,
    20: scenario_20,
    21: scenario_21,
    22: scenario_22,
    23: scenario_23,
    24: scenario_24,
    25: scenario_25,
}


def run_scenario(
    num: int, size: str = "tiny", *, model_scale: str | None = None,
    serve_eos: bool = False, quantized: bool | None = None,
    kv_int8: bool = False, kv_kernel: bool | str = "auto",
    spec: bool = False, spec_k: int = 4,
    spec_draft_layers: int | None = None,
    temperature: float = 0.0, top_k: int | None = None,
    top_p: float | None = None, replicas: int = 2,
    prefill_chunk: int | None = None,
) -> dict:
    if size not in _SIZES:
        raise ValueError(f"size must be one of {_SIZES}")
    if prefill_chunk is not None and num != 14:
        raise ValueError(
            "--prefill-chunk applies to scenario 14 (the chunked-prefill "
            "storm smoke)"
        )
    if num == 14:
        return SCENARIOS[14](size, prefill_chunk=prefill_chunk)
    if serve_eos and (num != 7 or model_scale is None):
        raise ValueError("--serve-eos applies to scenario 7 at a model scale")
    if quantized is not None and (model_scale is None or num not in (5, 7)):
        raise ValueError("--quantized applies to scenarios 5/7 at a model scale")
    if kv_int8 and num != 7:
        raise ValueError("--kv-int8 applies to scenario 7 (the slot pool)")
    if spec and num != 7:
        raise ValueError("--spec applies to scenario 7 (speculative serving)")
    if spec and kv_int8:
        raise ValueError(
            "--spec serves the compute-dtype pool (token-exactness is the "
            "contract); drop --kv-int8"
        )
    sampling = temperature != 0.0 or top_k is not None or top_p is not None
    if sampling and num != 7:
        raise ValueError(
            "--temperature/--top-k/--top-p apply to scenario 7 (the "
            "sampled serving path)"
        )
    if spec and sampling:
        raise ValueError(
            "--spec is greedy-only (the accept rule is the target's "
            "argmax); drop the sampling flags"
        )
    sample_kw = dict(temperature=temperature, top_k=top_k, top_p=top_p)
    spec_kw = dict(spec=spec, spec_k=spec_k, spec_draft_layers=spec_draft_layers)
    if num in (10, 11, 12, 13, 15, 16, 17, 18, 19, 20, 21, 23, 24, 25):
        return SCENARIOS[num](size, replicas=replicas)
    if num == 22:
        return SCENARIOS[22](size, replicas=1)
    if model_scale is not None:
        if num not in (5, 7):
            raise ValueError("model_scale applies to scenarios 5 and 7 only")
        if num == 7:
            return SCENARIOS[7](
                size, model_scale=model_scale, serve_eos=serve_eos,
                quantized=quantized, kv_int8=kv_int8, kv_kernel=kv_kernel,
                **spec_kw, **sample_kw,
            )
        return SCENARIOS[5](size, model_scale=model_scale, quantized=quantized)
    if kv_int8:
        return SCENARIOS[7](size, kv_int8=True, kv_kernel=kv_kernel, **sample_kw)
    if spec:
        return SCENARIOS[7](size, **spec_kw)
    if sampling:
        return SCENARIOS[7](size, **sample_kw)
    return SCENARIOS[num](size)

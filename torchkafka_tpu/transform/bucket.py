"""Length-bucketed batching for ragged record streams.

XLA compiles static shapes, so ragged text must pad — and padding every
record to the stream's maximum length burns MXU FLOPs and HBM on dead
tokens (a other tokens-mostly-short topic padded to 512 wastes >90% of the
batch). The TPU-idiomatic answer is length bucketing: a few fixed widths,
each its own static shape (one XLA compile per width, cached), rows routed
to the smallest width that fits.

``BucketBatcher`` drops into the stream where ``Batcher`` goes:

- the processor returns a VARIABLE-length 1-D array per record (or None
  to drop);
- rows land in the smallest bucket ≥ their length, padded with
  ``pad_value``; rows longer than the largest bucket are truncated to it
  (the same pad/truncate contract as ``fixed_width``);
- emitted batches are pytrees ``{"tokens": [B, W], "length": [B]}`` — the
  true pre-pad lengths ride along so consumers build attention masks
  without re-deriving them;
- ALL buckets share ONE interval ledger, so commit-exactly-the-batch
  holds even though batches emit out of arrival order across buckets (the
  ledger retires rows individually; a short row emitted early while a
  long row waits in a sparser bucket simply holds the watermark at the
  long row's offset — at-least-once, never a lost or skipped record).

The reference never faced this (its records are opaque blobs and torch
tolerates ragged collation, /root/reference/src/kafka_dataset.py:173-186);
this is net-new TPU-shaped capability on the SURVEY §7 "dynamic record
streams vs XLA static shapes" hard part.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from torchkafka_tpu.commit.ledger import OffsetLedger
from torchkafka_tpu.source.records import Record
from torchkafka_tpu.transform.batcher import Batch, Batcher


class BucketBatcher:
    """Routes variable-length 1-D rows into per-width ``Batcher``s sharing
    one ledger. Same ``add``/``flush_tails`` surface the stream drives."""

    def __init__(
        self,
        batch_size: int,
        boundaries: Sequence[int],
        ledger: OffsetLedger | None = None,
        pad_policy: str = "block",
        pad_value: int = 0,
    ) -> None:
        if isinstance(boundaries, (str, bytes)):
            # '512' would iterate as digit widths [5, 1, 2] — silent data
            # truncation; make it an immediate error instead.
            raise ValueError(
                f"bucket boundaries must be a sequence of ints, got "
                f"{boundaries!r}"
            )
        widths = sorted(set(int(w) for w in boundaries))
        if not widths or widths[0] <= 0:
            raise ValueError(f"bucket boundaries must be positive, got {boundaries}")
        self.ledger = ledger if ledger is not None else OffsetLedger()
        self.pad_policy = pad_policy
        self._widths = widths
        self._pad_value = pad_value
        self._batchers = {
            w: Batcher(batch_size, self.ledger, pad_policy) for w in widths
        }

    def _width_for(self, n: int) -> int:
        for w in self._widths:
            if n <= w:
                return w
        return self._widths[-1]  # longer than the largest bucket: truncate

    def add(self, element: Any, record: Record) -> Batch | None:
        if element is None:
            self.ledger.dropped(record)
            return None
        row = np.asarray(element)
        if row.ndim != 1:
            raise ValueError(
                f"bucketed processors must return 1-D rows, got shape "
                f"{row.shape}; fixed-shape pytrees belong in Batcher"
            )
        w = self._width_for(row.shape[0])
        n = min(row.shape[0], w)
        padded = np.full((w,), self._pad_value, dtype=row.dtype)
        padded[:n] = row[:n]
        return self._batchers[w].add(
            {"tokens": padded, "length": np.int32(n)}, record
        )

    def flush_tails(self) -> list[Batch]:
        """Every bucket's partial tail under the 'pad' policy (ascending
        width order); [] under 'block'."""
        out = []
        for w in self._widths:
            tail = self._batchers[w].flush()
            if tail is not None:
                out.append(tail)
        return out

    # NOTE: deliberately NO single-tail ``flush()`` — multiple buckets can
    # hold tails, and a Batcher-compat flush that returned only the first
    # would still have retired the others' offsets in the shared ledger
    # (committing past undelivered records). Callers must use flush_tails.

    @property
    def pending_in_batch(self) -> int:
        return sum(b.pending_in_batch for b in self._batchers.values())

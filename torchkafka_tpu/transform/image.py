"""Image codecs for image-bytes topics: PNG encode (producer/test side) and
the PNG-decoding chunk processor (ingest side).

This is BASELINE config 4's host-side hot path made real: the reference's
``_process`` hook exists precisely for per-record CPU work like image
decompression (/root/reference/src/kafka_dataset.py:173-186), and an image
ingest pipeline that skips the decompression measures the wrong thing
(VERDICT r2). The decode rides the native C++ path
(torchkafka_tpu.native.decode_png_rgb: one C call per poll chunk — zlib
inflate + scanline defilter straight into the batcher's buffer) with a
NumPy fallback of identical semantics.

The encoder is pure Python (zlib) and intentionally simple: 8-bit RGB,
non-interlaced, one IDAT chunk, selectable per-row filter. It exists so
producers/tests/benchmarks can mint REAL compressed images without an
image library dependency — not to compete with libpng on encode speed.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from torchkafka_tpu.source.records import Record
from torchkafka_tpu.transform.processor import chunked

_SIG = b"\x89PNG\r\n\x1a\n"


def _chunk(ctype: bytes, data: bytes) -> bytes:
    crc = zlib.crc32(ctype + data) & 0xFFFFFFFF
    return struct.pack(">I", len(data)) + ctype + data + struct.pack(">I", crc)


def _filter_rows(img: np.ndarray, filters: str | int) -> bytes:
    """Apply PNG scanline filters (the encode direction) and concatenate
    rows. ``filters``: an int 0-4 for every row, or 'cycle' to rotate
    through all five (exercises every defilter path on the decode side,
    like a real encoder's adaptive choice would)."""
    h, w, _ = img.shape
    stride = w * 3
    flat = img.reshape(h, stride).astype(np.int32)
    out = bytearray()
    for y in range(h):
        f = (y % 5) if filters == "cycle" else int(filters)
        cur = flat[y]
        prior = flat[y - 1] if y > 0 else np.zeros(stride, np.int32)
        left = np.concatenate([np.zeros(3, np.int32), cur[:-3]])
        if f == 0:
            enc = cur
        elif f == 1:
            enc = cur - left
        elif f == 2:
            enc = cur - prior
        elif f == 3:
            enc = cur - ((left + prior) >> 1)
        elif f == 4:
            up_left = np.concatenate([np.zeros(3, np.int32), prior[:-3]])
            p = left + prior - up_left
            pa = np.abs(p - left)
            pb = np.abs(p - prior)
            pc = np.abs(p - up_left)
            pred = np.where(
                (pa <= pb) & (pa <= pc), left, np.where(pb <= pc, prior, up_left)
            )
            enc = cur - pred
        else:
            raise ValueError(f"PNG filter must be 0-4 or 'cycle', got {filters}")
        out.append(f)
        out += (enc % 256).astype(np.uint8).tobytes()
    return bytes(out)


def encode_png_rgb(
    img: np.ndarray, *, filters: str | int = "cycle", level: int = 6
) -> bytes:
    """uint8 [h, w, 3] → a standards-conforming 8-bit RGB PNG payload."""
    if img.ndim != 3 or img.shape[2] != 3 or img.dtype != np.uint8:
        raise ValueError(f"expected uint8 [h, w, 3], got {img.dtype} {img.shape}")
    h, w, _ = img.shape
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    idat = zlib.compress(_filter_rows(img, filters), level)
    return _SIG + _chunk(b"IHDR", ihdr) + _chunk(b"IDAT", idat) + _chunk(b"IEND", b"")


def png_images(height: int, width: int):
    """Chunk processor: records of 8-bit RGB PNG bytes → uint8
    [K, height, width, 3] stacked images + keep mask (invalid or
    wrong-dimension records drop — the vectorized None-drop contract)."""

    @chunked
    def process(records: list[Record]):
        from torchkafka_tpu import native

        imgs, keep = native.decode_png_rgb(
            [r.value for r in records], height, width
        )
        mask = keep.astype(bool)
        if mask.all():
            return imgs, None
        if not mask.any():
            return None, mask
        return imgs[mask], mask  # batcher contract: kept rows + full mask

    return process

"""The user-extension point: per-record transforms.

Capability parity with the reference's single extension hook,
``KafkaDataset._process(record) -> data | None``
(/root/reference/src/kafka_dataset.py:173-186): a processor maps one record to
a pytree of fixed-shape NumPy arrays, or None to drop the record
(/root/reference/src/kafka_dataset.py:161-162, README.md:59 — the drop
contract). The TPU-facing difference is explicit in the type: outputs must be
*fixed-shape* arrays, because XLA compiles static shapes; ragged data must be
padded/truncated here, at the record level, where the user knows the domain.

Processors are plain callables — no subclassing required (though the compat
layer's KafkaDataset._process maps straight onto this).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

import numpy as np

from torchkafka_tpu.source.records import Record

# A processor maps a record to a pytree of np.ndarray (all leaves fixed-shape
# across records) or None to drop the record.
Processor = Callable[[Record], Optional[Any]]


def raw_bytes(length: int, dtype=np.uint8, pad_value: int = 0) -> Processor:
    """Record value -> fixed-length byte vector (truncate/zero-pad)."""

    def process(record: Record):
        buf = np.frombuffer(record.value[:length], dtype=np.uint8)
        if buf.shape[0] < length:
            buf = np.concatenate(
                [buf, np.full(length - buf.shape[0], pad_value, dtype=np.uint8)]
            )
        return buf.astype(dtype, copy=False)

    return process


def json_field(
    field: str,
    seq_len: int,
    tokenizer: Callable[[str], list[int]] | None = None,
    pad_id: int = 0,
    drop_invalid: bool = True,
) -> Processor:
    """JSON record -> int32 token ids of fixed ``seq_len`` (BASELINE config 2
    shape: JSON records -> tokenized int32 batches).

    Invalid JSON / missing field -> None (record dropped) when
    ``drop_invalid``, else raises. Default tokenizer is bytes-of-utf8 — a
    stand-in with the right shape; swap in a real tokenizer callable.
    """
    tok = tokenizer if tokenizer is not None else (lambda s: list(s.encode("utf-8")))

    def process(record: Record):
        try:
            obj = json.loads(record.value)
            text = obj[field]
            if not isinstance(text, str):
                raise TypeError(f"field {field!r} is {type(text).__name__}, not str")
            ids = tok(text)
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError, TypeError,
                AttributeError, IndexError):
            # One malformed record (non-object root, wrong-typed field,
            # tokenizer blowup) must drop, not kill the whole pipeline.
            if drop_invalid:
                return None
            raise
        ids = ids[:seq_len]
        out = np.full(seq_len, pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out

    return process


def compose(*fns: Callable) -> Processor:
    """Chain callables left-to-right; None short-circuits (drop)."""

    def process(record: Record):
        x: Any = record
        for f in fns:
            x = f(x)
            if x is None:
                return None
        return x

    return process

"""The user-extension point: per-record transforms.

Capability parity with the reference's single extension hook,
``KafkaDataset._process(record) -> data | None``
(/root/reference/src/kafka_dataset.py:173-186): a processor maps one record to
a pytree of fixed-shape NumPy arrays, or None to drop the record
(/root/reference/src/kafka_dataset.py:161-162, README.md:59 — the drop
contract). The TPU-facing difference is explicit in the type: outputs must be
*fixed-shape* arrays, because XLA compiles static shapes; ragged data must be
padded/truncated here, at the record level, where the user knows the domain.

Processors are plain callables — no subclassing required (though the compat
layer's KafkaDataset._process maps straight onto this).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

import numpy as np

from torchkafka_tpu.source.records import Record

# A processor maps a record to a pytree of np.ndarray (all leaves fixed-shape
# across records) or None to drop the record.
Processor = Callable[[Record], Optional[Any]]


def chunked(fn: Callable) -> Callable:
    """Mark ``fn(records: list[Record]) -> (stacked_pytree, keep_mask|None)``
    as a chunk processor: the stream hands it a whole poll chunk and it
    returns [K, ...]-stacked arrays (plus an optional boolean keep mask,
    False = drop — the vectorized form of the reference's None-drop contract).

    This is the throughput path: one Python call per poll chunk instead of
    per record, with decode work done as single NumPy (or native) ops.
    """
    fn.chunked = True  # type: ignore[attr-defined]
    return fn


def is_chunked(fn: Callable) -> bool:
    return bool(getattr(fn, "chunked", False))


def fixed_width(
    seq_len: int, dtype=np.int32, pad_value: int = 0, wire_dtype=None,
    wire_bits: int | None = None,
) -> Callable:
    """Chunk processor for fixed-width binary records: each record value is
    ``seq_len`` items of ``dtype`` (the BASELINE token-stream shape). Exact-
    width chunks decode with one join + one frombuffer (two memcpy-scale ops
    for the whole chunk); ragged stragglers fall back to a per-record
    pad/truncate. Uses the native C++ decoder when built (torchkafka_tpu.native).

    ``wire_dtype``: optional narrower dtype the decoded rows are cast to
    before leaving the host — the batch travels host→device in this dtype.
    Host↔device bandwidth is the scarce resource on an ingest pipeline
    (HBM/PCIe/ICI all beat it); token ids under 65536 in ``uint16`` halve
    the wire bytes and gather into embeddings on-device without widening.
    The cast asserts the values fit (overflow would corrupt ids silently).

    ``wire_bits``: go below byte granularity — rows pack into a dense
    little-endian bit stream (native.pack_bits, one C call per chunk) and
    travel as uint8[packed_width]; the consumer unpacks ON DEVICE with
    ``ops.bitpack.unpack_bits(batch, wire_bits, seq_len)`` (three gathers
    + shift + mask, fused into the embedding lookup). A 15-bit vocabulary
    rides the wire at 15/16 of uint16. Exclusive with ``wire_dtype``;
    requires non-negative values < 2^wire_bits (checked per chunk).
    """
    if wire_bits is not None and wire_dtype is not None:
        raise ValueError("wire_bits and wire_dtype are exclusive")
    if wire_bits is not None and not 1 <= wire_bits <= 16:
        raise ValueError("wire_bits must be in [1, 16]")
    if wire_bits is not None and not np.issubdtype(np.dtype(dtype), np.integer):
        # The range guard below cannot see fractional parts — a float 3.7
        # passes [0, 2^bits) and then truncates silently in the pack.
        raise ValueError("wire_bits requires an integer record dtype")
    if wire_bits is not None and not 0 <= pad_value < (1 << wire_bits):
        # A short record padded with an out-of-range value would trip the
        # per-chunk range guard with an error blaming the RECORDS; catch
        # the misconfiguration where it lives, at construction.
        raise ValueError(
            f"pad_value {pad_value} outside [0, 2^{wire_bits}) — padded "
            "rows could not be bit-packed"
        )

    @chunked
    def process(records: list[Record]):
        from torchkafka_tpu import native

        rows = native.gather_rows([r.value for r in records], seq_len, dtype, pad_value)
        if wire_bits is not None:
            if rows.size and (rows.min() < 0 or rows.max() >= 1 << wire_bits):
                raise ValueError(
                    f"record values outside [0, 2^{wire_bits}) — bit "
                    "packing would corrupt them"
                )
            return native.pack_bits(rows, wire_bits), None
        if wire_dtype is not None:
            info = np.iinfo(wire_dtype)
            if rows.size and (rows.min() < info.min or rows.max() > info.max):
                raise ValueError(
                    f"record values outside {np.dtype(wire_dtype).name} range "
                    f"[{info.min}, {info.max}] — narrowing would corrupt them"
                )
            rows = rows.astype(wire_dtype)
        return rows, None

    return process


def json_tokens(
    field: str, seq_len: int, pad_id: int = 0
) -> Callable:
    """Chunk processor: flat-JSON records → int32[seq_len] token rows via the
    native C++ field scanner (one C call per poll chunk; utf-8-byte
    tokenization, the same stand-in tokenizer as ``json_field``'s default —
    but raw bytes, escape sequences are not decoded). Records whose field is
    missing/invalid are dropped (keep mask), the vectorized form of the
    reference's None-drop (/root/reference/src/kafka_dataset.py:161-162).

    Use ``chunk_of(json_field(...))`` instead when you need full JSON
    semantics (escape decoding, nested objects, custom tokenizers).
    """

    @chunked
    def process(records: list[Record]):
        from torchkafka_tpu import native

        tokens, keep = native.json_tokens_scan(
            [r.value for r in records], field, seq_len, pad_id
        )
        mask = keep.astype(bool)
        if mask.all():
            return tokens, None
        if not mask.any():
            return None, mask
        return tokens[mask], mask

    return process


def chunk_of(per_record: Processor) -> Callable:
    """Lift a per-record processor into a chunk processor (convenience — no
    speedup, but lets one code path serve both)."""

    @chunked
    def process(records: list[Record]):
        elements = [per_record(r) for r in records]
        keep = np.array([e is not None for e in elements], dtype=bool)
        kept = [e for e in elements if e is not None]
        if not kept:
            return None, keep
        import jax

        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *kept)
        return stacked, keep

    return process


def raw_bytes(length: int, dtype=np.uint8, pad_value: int = 0) -> Processor:
    """Record value -> fixed-length byte vector (truncate/zero-pad)."""

    def process(record: Record):
        buf = np.frombuffer(record.value[:length], dtype=np.uint8)
        if buf.shape[0] < length:
            buf = np.concatenate(
                [buf, np.full(length - buf.shape[0], pad_value, dtype=np.uint8)]
            )
        return buf.astype(dtype, copy=False)

    return process


def json_field(
    field: str,
    seq_len: int,
    tokenizer: Callable[[str], list[int]] | None = None,
    pad_id: int = 0,
    drop_invalid: bool = True,
) -> Processor:
    """JSON record -> int32 token ids of fixed ``seq_len`` (BASELINE config 2
    shape: JSON records -> tokenized int32 batches).

    Invalid JSON / missing field -> None (record dropped) when
    ``drop_invalid``, else raises. Default tokenizer is bytes-of-utf8 — a
    stand-in with the right shape; swap in a real tokenizer callable.
    """
    tok = tokenizer if tokenizer is not None else (lambda s: list(s.encode("utf-8")))

    def process(record: Record):
        try:
            obj = json.loads(record.value)
            text = obj[field]
            if not isinstance(text, str):
                raise TypeError(f"field {field!r} is {type(text).__name__}, not str")
            ids = tok(text)
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError, TypeError,
                AttributeError, IndexError):
            # One malformed record (non-object root, wrong-typed field,
            # tokenizer blowup) must drop, not kill the whole pipeline.
            if drop_invalid:
                return None
            raise
        ids = ids[:seq_len]
        out = np.full(seq_len, pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out

    return process


def compose(*fns: Callable) -> Processor:
    """Chain callables left-to-right; None short-circuits (drop)."""

    def process(record: Record):
        x: Any = record
        for f in fns:
            x = f(x)
            if x is None:
                return None
        return x

    return process

"""Transform layer: per-record processors and fixed-shape batching."""

from torchkafka_tpu.transform.batcher import Batch, Batcher
from torchkafka_tpu.transform.processor import (
    Processor,
    compose,
    json_field,
    raw_bytes,
)

__all__ = ["Batch", "Batcher", "Processor", "compose", "json_field", "raw_bytes"]

"""Transform layer: per-record / per-chunk processors and fixed-shape batching."""

from torchkafka_tpu.transform.batcher import Batch, Batcher
from torchkafka_tpu.transform.bucket import BucketBatcher
from torchkafka_tpu.transform.image import encode_png_rgb, png_images
from torchkafka_tpu.transform.processor import (
    Processor,
    chunk_of,
    chunked,
    compose,
    fixed_width,
    is_chunked,
    json_field,
    json_tokens,
    raw_bytes,
)

__all__ = [
    "Batch",
    "Batcher",
    "BucketBatcher",
    "Processor",
    "chunk_of",
    "chunked",
    "compose",
    "encode_png_rgb",
    "fixed_width",
    "is_chunked",
    "json_field",
    "json_tokens",
    "png_images",
    "raw_bytes",
]

"""Transform layer: per-record / per-chunk processors and fixed-shape batching."""

from torchkafka_tpu.transform.batcher import Batch, Batcher
from torchkafka_tpu.transform.processor import (
    Processor,
    chunk_of,
    chunked,
    compose,
    fixed_width,
    is_chunked,
    json_field,
    json_tokens,
    raw_bytes,
)

__all__ = [
    "Batch",
    "Batcher",
    "Processor",
    "chunk_of",
    "chunked",
    "compose",
    "fixed_width",
    "is_chunked",
    "json_field",
    "json_tokens",
    "raw_bytes",
]

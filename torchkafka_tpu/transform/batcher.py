"""Fixed-shape batch assembly with carry-over accounting.

Replaces the reference's L2 (torch DataLoader collation, SURVEY.md §1) with a
batcher built for XLA's static-shape world. The reference never faced this
problem — DataLoader happily emits ragged final batches; XLA recompiles on
every new shape, so we never change shape. Policies:

- ``block`` (default): only full batches are emitted; a partial tail waits
  for more records. Its records stay *pending* in the ledger, so they are
  excluded from every commit watermark until actually emitted — the
  carry-over rule that makes the reference's round-robin worker↔batch
  correspondence assumption (SURVEY.md §2 quirk 4) unnecessary.
- ``pad``: ``flush()`` zero-pads the tail to the batch size and reports
  ``valid_count``; downstream masks with ``batch.valid_mask()``.

Elements are pytrees of fixed-shape NumPy arrays; leaves are stacked into
preallocated ``[B, ...]`` buffers (one memcpy per element per leaf — the hot
host path; see native/ for the C++ fast path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

import numpy as np

from torchkafka_tpu.commit.ledger import OffsetLedger
from torchkafka_tpu.source.records import ChunkIndex, Record, TopicPartition

try:
    from jax import tree_util as _tree
except ImportError:  # pragma: no cover - jax is a hard dep, but keep honest
    _tree = None


@dataclasses.dataclass
class Batch:
    """One host-local batch: stacked arrays + how many rows are real."""

    data: Any  # pytree of np.ndarray with leading dim == batch_size
    valid_count: int
    offsets: dict[TopicPartition, int]  # committable snapshot for this batch

    @property
    def batch_size(self) -> int:
        leaves = _tree.tree_leaves(self.data)
        return int(leaves[0].shape[0]) if leaves else 0

    def valid_mask(self) -> np.ndarray:
        """Boolean [B] mask; rows past valid_count are padding."""
        return np.arange(self.batch_size) < self.valid_count


class Batcher:
    """Accumulates processed elements into fixed-size batches.

    Drives the ledger: ``add`` marks drops, ``_emit`` marks emissions and
    snapshots the committable offsets at exactly that moment.
    """

    def __init__(
        self,
        batch_size: int,
        ledger: OffsetLedger | None = None,
        pad_policy: str = "block",
    ) -> None:
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if pad_policy not in ("block", "pad"):
            raise ValueError(f"pad_policy must be 'block'|'pad', got {pad_policy!r}")
        self.batch_size = batch_size
        self.ledger = ledger if ledger is not None else OffsetLedger()
        self.pad_policy = pad_policy
        self._treedef = None
        self._buffers: list[np.ndarray] | None = None
        self._fill = 0
        # Row identity, columnar: which (partition, offset) occupies each
        # buffered row — the ledger accounting needs nothing more, and arrays
        # keep the per-row cost at memcpy level (no Record objects held).
        self._tp_table: list[TopicPartition] = []
        self._tp_ids: dict[TopicPartition, int] = {}
        self._row_tp = np.empty(batch_size, np.int32)
        self._row_off = np.empty(batch_size, np.int64)

    def _init_buffers(self, element: Any) -> None:
        leaves, treedef = _tree.tree_flatten(element)
        for i, leaf in enumerate(leaves):
            if not isinstance(leaf, np.ndarray):
                leaves[i] = np.asarray(leaf)
        self._treedef = treedef
        self._buffers = [
            np.zeros((self.batch_size, *leaf.shape), dtype=leaf.dtype) for leaf in leaves
        ]

    def add(self, element: Any, record: Record) -> Batch | None:
        """Add one processed element (None = drop). Returns a full Batch when
        the element completes one, else None.

        ``record`` must already be ``ledger.fetched``-registered by the caller
        (the stream does this at poll time).
        """
        if element is None:
            self.ledger.dropped(record)
            return None
        if self._buffers is None:
            self._init_buffers(element)
        leaves = _tree.tree_leaves(element)
        if len(leaves) != len(self._buffers):
            raise ValueError("element structure changed between records")
        for buf, leaf in zip(self._buffers, leaves):
            arr = np.asarray(leaf)
            if arr.shape != buf.shape[1:] or arr.dtype != buf.dtype:
                raise ValueError(
                    f"element leaf shape/dtype {arr.shape}/{arr.dtype} does not "
                    f"match batch buffer {buf.shape[1:]}/{buf.dtype}; processors "
                    f"must emit fixed shapes (pad/truncate per record)"
                )
            buf[self._fill] = arr
        self._row_tp[self._fill] = self._tp_id(record.tp)
        self._row_off[self._fill] = record.offset
        self._fill += 1
        if self._fill == self.batch_size:
            return self._emit()
        return None

    def _tp_id(self, tp: TopicPartition) -> int:
        i = self._tp_ids.get(tp)
        if i is None:
            i = self._tp_ids[tp] = len(self._tp_table)
            self._tp_table.append(tp)
        return i

    def add_many(
        self,
        stacked: Any,
        records: "list[Record] | ChunkIndex",
        keep: np.ndarray | None = None,
    ) -> list[Batch]:
        """Bulk add: the chunk-processor path. ``records`` identifies the
        chunk's rows — a list[Record] or (hot path) a ChunkIndex, which
        carries the same identity as arrays with no per-row objects.
        ``keep`` is an optional boolean [len(records)] mask; False rows are
        drops, and ``stacked`` holds only the kept rows (sum(keep) of them)
        in record order. With no mask, ``stacked`` covers every record.
        ``stacked=None`` means the whole chunk was dropped: every offset is
        retired immediately (a pending-forever chunk would freeze the
        partition's commit watermark).
        Copies land as array slices, not per-record memcpys. Returns every
        full Batch completed by this chunk (possibly several).
        """
        index = (
            records
            if isinstance(records, ChunkIndex)
            else ChunkIndex.from_records(records)
        )
        # Remap the chunk's partition-id space into the batcher's.
        remap = np.fromiter(
            (self._tp_id(tp) for tp in index.tps), np.int32, len(index.tps)
        )
        tp_idx = remap[index.tp_idx] if len(index.tps) else index.tp_idx
        offsets = index.offsets
        if stacked is None:
            # Whole chunk dropped: every offset resolves as a drop NOW, else
            # the records stay pending forever and freeze the partition's
            # commit watermark.
            self._retire(tp_idx, offsets)
            return []
        if keep is not None:
            keep = np.asarray(keep, bool)
            if keep.shape[0] != offsets.shape[0]:
                raise ValueError(
                    f"keep mask has {keep.shape[0]} rows, chunk has {offsets.shape[0]}"
                )
            self._retire(tp_idx[~keep], offsets[~keep])  # drops resolve now
            tp_idx = tp_idx[keep]
            offsets = offsets[keep]
            if offsets.shape[0] == 0:
                return []
        leaves, treedef = _tree.tree_flatten(stacked)
        leaves = [np.asarray(leaf) for leaf in leaves]
        if self._buffers is None:
            self._treedef = treedef
            self._buffers = [
                np.zeros((self.batch_size, *leaf.shape[1:]), dtype=leaf.dtype)
                for leaf in leaves
            ]
        if len(leaves) != len(self._buffers):
            raise ValueError("element structure changed between chunks")
        n = leaves[0].shape[0]
        if n != offsets.shape[0]:
            raise ValueError(f"chunk has {n} rows but {offsets.shape[0]} records")
        out: list[Batch] = []
        i = 0
        while i < n:
            take = min(self.batch_size - self._fill, n - i)
            for buf, leaf in zip(self._buffers, leaves):
                if leaf.shape[1:] != buf.shape[1:] or leaf.dtype != buf.dtype:
                    raise ValueError(
                        f"chunk leaf shape/dtype {leaf.shape[1:]}/{leaf.dtype} does "
                        f"not match batch buffer {buf.shape[1:]}/{buf.dtype}"
                    )
                buf[self._fill : self._fill + take] = leaf[i : i + take]
            self._row_tp[self._fill : self._fill + take] = tp_idx[i : i + take]
            self._row_off[self._fill : self._fill + take] = offsets[i : i + take]
            self._fill += take
            i += take
            if self._fill == self.batch_size:
                out.append(self._emit())
        return out

    def _retire(self, tp_idx: np.ndarray, offsets: np.ndarray) -> None:
        """Mark rows done in the ledger, grouped per partition (each group's
        offsets stay ascending, so the ledger's O(1) run path applies)."""
        if offsets.shape[0] == 0:
            return
        for i in np.unique(tp_idx):
            self.ledger.done_array(self._tp_table[int(i)], offsets[tp_idx == i])

    def flush(self) -> Batch | None:
        """Emit the partial tail (pad policy) or nothing (block policy —
        the tail stays pending and uncommitted)."""
        if self._fill == 0 or self.pad_policy != "pad":
            return None
        return self._emit()

    def flush_tails(self) -> list["Batch"]:
        """Uniform flush surface shared with BucketBatcher (which can hold
        one tail per bucket)."""
        tail = self.flush()
        return [tail] if tail is not None else []

    def _emit(self) -> Batch:
        assert self._buffers is not None
        # Retire the buffered rows from the columnar identity arrays *before*
        # snapshotting, so the snapshot's watermark covers exactly this batch.
        self._retire(self._row_tp[: self._fill], self._row_off[: self._fill])
        batch = Batch(
            data=_tree.tree_unflatten(self._treedef, self._buffers),
            valid_count=self._fill,
            offsets=self.ledger.snapshot(),
        )
        # Fresh buffers: the emitted batch owns the old ones (zero-copy handoff).
        leaves = _tree.tree_leaves(batch.data)
        self._buffers = [np.zeros_like(leaf) for leaf in leaves]
        self._fill = 0
        return batch

    @property
    def pending_in_batch(self) -> int:
        """Elements accumulated but not yet emitted (the carry-over)."""
        return self._fill

    def feed(self, processed: Iterator[tuple[Any, Record]]) -> Iterator[Batch]:
        """Convenience: drain an iterator of (element, record) into batches."""
        for element, record in processed:
            out = self.add(element, record)
            if out is not None:
                yield out

"""Speculative continuous-batching serving: spec decode inside the slot server.

Composes the repo's two flagship inference features, which had never met:
``models/spec_decode.py`` (draft k tokens, verify all k+1 positions in ONE
multi-query target dispatch, accept the longest matching prefix) and
``serve.py``'s ``StreamingGenerator`` (fixed slot pool over a Kafka prompt
topic, per-completion offset retirement through the interval ledger). The
result is the combination every production server runs — continuous
batching + speculation — as a drop-in server: ``SpecStreamingGenerator``
replaces one class name and everything else (admission loop, commit
cadence, output topic, chaos behavior, metrics) is inherited UNCHANGED.

How the composition works: ``StreamingGenerator.run()`` treats the slot
state as an OPAQUE tuple threaded through ``self._admit_fn`` /
``self._tick_fn``. This subclass only overrides ``_build`` to install a
speculative admit/tick pair whose state tuple carries (target pool, draft
pool, acceptance counters); the run loop cannot tell the difference. One
"tick" becomes one SPECULATIVE ROUND per active slot:

1. the draft proposes k greedy tokens autoregressively (k+1 cheap
   single-query steps — the last only ingests proposal k so the draft
   cache stays contiguous across full-accept rounds, spec_decode's rule);
2. the target scores all k+1 positions in one ``_multi_step`` verify
   (per-row start positions — exactly the serving tick generalised to
   S = k+1 queries);
3. per slot, the longest draft prefix matching the target's own argmax is
   accepted and the target's correction/bonus token appended — every
   emitted token is the TARGET's greedy choice, so the server is
   token-exact vs the plain ``StreamingGenerator`` (greedy) and the draft
   sets only the speed (differential-tested in tests/test_serve_spec.py).

Static shapes throughout, the serving discipline: the round emits a
DYNAMIC per-slot count (1..k+1) but it lives in position bookkeeping —
``pos`` advances by the per-slot accepted length, the gen buffer takes a
static k+1-step masked one-hot write, EOS stops emission mid-round via a
static cumulative mask. Rollback is free exactly as in spec_decode: both
pools are written speculatively and rejected positions become stale
entries beyond the per-slot watermark, overwritten write-before-attend by
the next round (the pool carries a k-position overshoot margin).

Commit semantics are untouched BY CONSTRUCTION: completions retire
offsets through the same ledger calls in the inherited ``run()``, so
at-least-once-per-prompt and commit-watermark exactness hold under
speculation — including under injected commit failures (chaos-tested:
speculation never changes which offsets commit).

Greedy-only (temperature=0): the exactness contract is what makes the
draft a pure speed knob. Compute-dtype KV only (int8 pools and the
int8-only Pallas read are validated out with a clear error — both give
up or bypass the exactness contract speculation is built on), but the
MESH composes: both models' params commit to their serving layouts,
the verify/draft multi-query math is plain XLA, and GSPMD shards it
from the layouts alone — token-exact vs single-device spec serving
(differential-tested), dense and paged pools alike.

Measured acceptance is a first-class output: the state tuple carries
device-side (rounds, proposed, accepted) counters and ``spec_stats()``
reports them, so harness scenario 7 ``--spec`` and
``benchmarks/bench_spec.py --serve`` publish the MEASURED α of a real
checkpoint, not a hypothetical point on the i.i.d. curve.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from torchkafka_tpu.models.generate import KVCache, _project_qkv, prefill
from torchkafka_tpu.models.quant import embed_rows, load_weight
from torchkafka_tpu.models.spec_decode import _multi_step, truncated_draft
from torchkafka_tpu.models.transformer import _rms_norm, _rope
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.serve import StreamingGenerator
from torchkafka_tpu.utils import tracing as xprof


class SpecStreamingGenerator(StreamingGenerator):
    """Continuous-batching server that decodes speculatively per slot.

    ``draft_params``/``draft_cfg``: any same-vocab draft model (given
    together), or omit both to build the self-speculative layer-skip
    draft — ``truncated_draft(params, cfg, draft_layers)`` — from the
    target itself (``draft_layers`` defaults to half the target's
    layers). ``k``: draft tokens proposed per verify dispatch.
    ``ticks_per_sync`` now counts speculative ROUNDS per device dispatch
    (each round advances an active slot by 1..k+1 tokens, vs exactly 1
    for a plain tick).
    """

    def __init__(
        self,
        consumer,
        params,
        cfg,
        *,
        draft_params=None,
        draft_cfg=None,
        draft_layers: int | None = None,
        k: int = 4,
        **kwargs,
    ) -> None:
        if kwargs.get("temperature", 0.0) != 0.0:
            raise ValueError(
                "speculative serving is greedy-only: the accept rule "
                "compares the draft against the target's argmax, which is "
                "what buys token-exactness vs plain serving (sampled "
                "speculation needs the rejection-sampling rule — not "
                "implemented)"
            )
        if kwargs.get("kv_dtype") is not None:
            raise ValueError(
                "speculative serving keeps the compute-dtype slot pool: "
                "int8 KV gives up token-exactness, the one contract "
                "speculation is built on"
            )
        if kwargs.get("kv_kernel", "auto") is True:
            raise ValueError(
                "kv_kernel=True cannot be honored: the Pallas decode "
                "kernel reads one query per slot, not the k+1-query verify"
            )
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "draft_params and draft_cfg must be given together "
                "(or neither, for the layer-truncated self-draft)"
            )
        if draft_params is None:
            if draft_layers is None:
                draft_layers = max(1, cfg.n_layers // 2)
            draft_params, draft_cfg = truncated_draft(params, cfg, draft_layers)
        elif draft_layers is not None:
            raise ValueError(
                "draft_layers applies to the self-truncated draft only — "
                "an explicit draft_params/draft_cfg pair already fixes "
                "the draft's depth"
            )
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft and target must share a vocab: "
                f"{draft_cfg.vocab_size} != {cfg.vocab_size}"
            )
        if kwargs.get("mesh") is not None:
            # Model-sharded spec serving: the DRAFT commits to the same
            # serving layouts as the target (the base __init__ places
            # the target tree); the verify/draft multi-query math is
            # plain XLA, so GSPMD shards it from the layouts alone —
            # exactly the dense server's design rule. Both models must
            # satisfy the mesh divisibilities.
            from torchkafka_tpu.models.generate import (
                check_serving_mesh,
                serving_shardings,
            )

            mesh = kwargs["mesh"]
            check_serving_mesh(
                draft_cfg, mesh, batch=kwargs.get("slots", 8)
            )
            draft_params = jax.device_put(
                draft_params, serving_shardings(draft_cfg, mesh, draft_params)
            )
        self._k = int(k)
        self._draft_params = draft_params
        self._draft_cfg = draft_cfg
        super().__init__(consumer, params, cfg, **kwargs)

    def _build(self) -> None:
        cfg, dcfg, k = self._cfg, self._draft_cfg, self._k
        B, P = self._slots, self._prompt_len
        max_new = self._max_new
        eos_id = self._eos_id
        # Overshoot margin: a round starting at the per-slot watermark
        # ``pos`` (<= P + max_new - 2 for a slot still active) writes
        # verify k/v at [pos, pos + k] — stale beyond the accepted length,
        # overwritten write-before-attend next round, but the pool must
        # hold them. (RoPE beyond cfg.max_seq_len is extrapolation only
        # for those never-attended stale tails.)
        self._max_len = M = P + max_new + k
        self._kv_kernel = False  # the base flag; never engaged here
        # The resolved backend for metrics (spec pools are compute-dtype
        # by validation, so the kernel never engages; pages and mesh
        # compose — the probe validates the same exclusions as the base).
        from torchkafka_tpu.kvcache import resolve_kv_backend

        self._kv_backend = resolve_kv_backend(
            cfg, mesh=self._mesh, kv_dtype=None,
            kv_kernel=self._kv_kernel_opt, kv_pages=self._kv_pages,
            max_len=M, slots=B, backend=jax.default_backend(),
        )
        mesh = self._mesh
        if self._kv_pages is not None and self._paged_setup():
            # Paged pools for BOTH models under ONE block table (same
            # block ids address target and draft tensors), so a radix
            # prefix hit reuses both models' cached prompt k/v.
            self._build_paged()
            return

        def admit(params_pair, state, last_tok, pos, gen, prompts,
                  admit_mask, key):
            """Prefill BOTH models on the full [B, P] batch; merge admitted
            rows into both pools. Token 0 comes from the TARGET's logits
            (greedy) — identical to the plain server's admit, so the two
            servers' completions start from the same token."""
            tparams, dparams = params_pair
            t_k, t_v, d_k, d_v, acc, prop, rounds = state
            t_logits, t_fresh = prefill(tparams, cfg, prompts, M, mesh)
            _d_logits, d_fresh = prefill(dparams, dcfg, prompts, M, mesh)
            sel = admit_mask[None, :, None, None, None]
            t_k = jnp.where(sel, t_fresh.k, t_k)
            t_v = jnp.where(sel, t_fresh.v, t_v)
            d_k = jnp.where(sel, d_fresh.k, d_k)
            d_v = jnp.where(sel, d_fresh.v, d_v)
            tok0 = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            last_tok = jnp.where(admit_mask, tok0, last_tok)
            pos = jnp.where(admit_mask, P, pos)
            gen = jnp.where(admit_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(admit_mask, tok0, gen[:, 0]))
            return (t_k, t_v, d_k, d_v, acc, prop, rounds), last_tok, pos, gen

        K = self._ticks_per_sync

        def tick_block(params_pair, state, last_tok, pos, gen, active_in, key):
            """K speculative rounds in one dispatch, done mask latched like
            the plain tick block. Invariant per slot: ``pos`` is the
            sequence position of ``last_tok`` (whose k/v is written by the
            NEXT verify), and gen[0 .. pos - P] holds the emitted tokens."""
            tparams, dparams = params_pair

            def one(carry, _):
                state, last_tok, pos, gen, done_latch, n_out = carry
                t_k, t_v, d_k, d_v, acc, prop, rounds = state
                act = active_in & ~done_latch

                # k+1 draft steps for k proposals — the last step only
                # INGESTS proposal k so the draft cache has an entry at
                # every accepted position after a full-accept round
                # (spec_decode's contiguity rule; see its body comment).
                def dbody(c, j):
                    dc, tok = c
                    logits, dc = _multi_step(
                        dparams, dcfg, dc, tok[:, None], pos + j
                    )
                    nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                    return (dc, nxt), nxt

                (dc, _), d_toks = lax.scan(
                    dbody, (KVCache(d_k, d_v), last_tok), jnp.arange(k + 1)
                )
                d_k, d_v = dc.k, dc.v
                d = jnp.transpose(d_toks[:k])  # [B, k]

                # One multi-query verify at per-slot start positions: the
                # serving tick generalised to S = k+1 (same write/mask
                # discipline — spec_decode._multi_step IS the sibling the
                # serve docstrings point at).
                v_in = jnp.concatenate([last_tok[:, None], d], axis=1)
                t_logits, tc = _multi_step(
                    tparams, cfg, KVCache(t_k, t_v), v_in, pos
                )
                t_k, t_v = tc.k, tc.v
                tga = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

                match = tga[:, :k] == d
                n_acc = jnp.sum(
                    jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
                )
                corr = jnp.take_along_axis(tga, n_acc[:, None], axis=1)[:, 0]

                # Emit accepted drafts then the correction/bonus — static
                # k+1-step masked one-hot writes over [B, max_new], like
                # the plain tick's gen write. Three static stop rules per
                # candidate j: past the accepted length (j > n_acc), past
                # the buffer (j >= rem), or after an earlier EOS in this
                # round (alive latch). Every candidate is a TARGET-greedy
                # token, so emission order equals plain serving's.
                emitted_before = pos - P + 1
                rem = max_new - emitted_before
                idxbuf = jnp.arange(max_new)[None, :]
                alive = act
                n_emit = jnp.zeros_like(pos)
                new_last = last_tok
                eos_hit = jnp.zeros_like(act)
                for j in range(k + 1):
                    tok_j = d[:, j] if j < k else corr
                    tok_j = jnp.where(j < n_acc, tok_j, corr)
                    emit = alive & (j <= n_acc) & (j < rem)
                    sel = (
                        idxbuf == (emitted_before + j)[:, None]
                    ) & emit[:, None]
                    gen = jnp.where(sel, tok_j[:, None], gen)
                    n_emit = n_emit + emit.astype(jnp.int32)
                    new_last = jnp.where(emit, tok_j, new_last)
                    if eos_id is not None:
                        # Same rule as the plain server: EOS counts on
                        # decode outputs only (gen index >= 1 — always
                        # true here since emitted_before >= 1), and the
                        # EOS token itself is emitted.
                        hit = emit & (tok_j == eos_id)
                        eos_hit = eos_hit | hit
                        alive = alive & ~hit
                emitted_after = emitted_before + n_emit
                done_now = act & (eos_hit | (emitted_after >= max_new))
                n_out = jnp.where(done_now, emitted_after, n_out)
                pos = jnp.where(act & ~done_now, pos + n_emit, pos)
                last_tok = jnp.where(act, new_last, last_tok)

                # Acceptance counters (device-side; spec_stats() fetches):
                # α = accepted / proposed over every live round — the
                # measured number PERF.md's speedup row is built on.
                n_act = jnp.sum(act.astype(jnp.int32))
                acc = acc + jnp.sum(jnp.where(act, n_acc, 0))
                prop = prop + k * n_act
                rounds = rounds + (n_act > 0).astype(jnp.int32)
                done_latch = done_latch | done_now
                state = (t_k, t_v, d_k, d_v, acc, prop, rounds)
                return (state, last_tok, pos, gen, done_latch, n_out), None

            done0 = jnp.zeros((B,), bool)
            n0 = jnp.zeros((B,), jnp.int32)
            (state, last_tok, pos, gen, done, n_out), _ = lax.scan(
                one, (state, last_tok, pos, gen, done0, n0), None, length=K
            )
            return state, last_tok, pos, gen, done, n_out

        def resume_admit(params_pair, state, last_tok, pos, gen, seq, slot,
                         emitted_row, g):
            """Journal warm resume, spec flavor: BOTH models' cache rows
            prefilled with prompt + journaled tokens in one dispatch (the
            base class's resume_admit over the two-pool state). The
            restored position invariant is the spec one unchanged: pos is
            last_tok's sequence position, whose k/v the NEXT verify
            writes."""
            tparams, dparams = params_pair
            t_k, t_v, d_k, d_v, acc, prop, rounds = state
            _tl, t_fresh = prefill(tparams, cfg, seq, M, mesh)
            _dl, d_fresh = prefill(dparams, dcfg, seq, M, mesh)
            t_k = lax.dynamic_update_slice(
                t_k, t_fresh.k.astype(t_k.dtype), (0, slot, 0, 0, 0)
            )
            t_v = lax.dynamic_update_slice(
                t_v, t_fresh.v.astype(t_v.dtype), (0, slot, 0, 0, 0)
            )
            d_k = lax.dynamic_update_slice(
                d_k, d_fresh.k.astype(d_k.dtype), (0, slot, 0, 0, 0)
            )
            d_v = lax.dynamic_update_slice(
                d_v, d_fresh.v.astype(d_v.dtype), (0, slot, 0, 0, 0)
            )
            last_tok = last_tok.at[slot].set(emitted_row[g - 1])
            pos = pos.at[slot].set(P + g - 1)
            gen = lax.dynamic_update_slice(
                gen, emitted_row[None, :], (slot, 0)
            )
            return (
                (t_k, t_v, d_k, d_v, acc, prop, rounds), last_tok, pos, gen
            )

        # Same dispatch shape as the base: donate the state tuple, pass
        # BOTH param trees as arguments (a closed-over tree lowers as
        # jaxpr constants — the base _build's note).
        _admit = jax.jit(admit, donate_argnums=(1,))
        _tick = jax.jit(tick_block, donate_argnums=(1,))
        _resume = jax.jit(resume_admit, donate_argnums=(1,))
        self._admit_fn = lambda *a: _admit(
            (self._params, self._draft_params), *a
        )
        self._tick_fn = lambda *a: _tick(
            (self._params, self._draft_params), *a
        )
        self._resume_exec = lambda *a: _resume(
            (self._params, self._draft_params), *a
        )
        # decode_roofline's raw hook passes only the target tree; close
        # over the draft (a 45M-class self-draft — small enough that the
        # constant-lowering cost the base avoids for 8B trees is fine).
        # NOTE its byte accounting stays target-only: the reported
        # roofline % under-counts the draft's extra reads.
        self._tick_block_raw = (
            lambda params, *a: tick_block((params, self._draft_params), *a)
        )

        nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        dl, dkh, ddh = dcfg.n_layers, dcfg.n_kv_heads, dcfg.head_dim
        self._caches = (
            jnp.zeros((nl, B, M, kh, dh), cfg.dtype),
            jnp.zeros((nl, B, M, kh, dh), cfg.dtype),
            jnp.zeros((dl, B, M, dkh, ddh), dcfg.dtype),
            jnp.zeros((dl, B, M, dkh, ddh), dcfg.dtype),
            # accepted / proposed / rounds — three DISTINCT buffers (the
            # state tuple is donated; one buffer donated thrice is an
            # XLA error).
            jnp.zeros((), jnp.int32).copy(),
            jnp.zeros((), jnp.int32).copy(),
            jnp.zeros((), jnp.int32).copy(),
        )
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._gen = jnp.zeros((B, max_new), jnp.int32)

    def _build_paged(self) -> None:
        """Speculative serving over the paged pool (``kv_pages=``).

        Same speculative round as the dense build — k+1 draft steps, one
        multi-query verify, target-argmax accept — but both models' slot
        caches are block pools ``[L, NB, bs, K, Dh]`` addressed through
        ONE per-slot block table, and admission goes through the base
        class's radix match → link → suffix-prefill path (both pools
        prefilled per record; a prefix hit skips BOTH models' prompt
        re-prefill). Verify/rollback respect block boundaries by
        construction: the verify's [pos, pos + k] writes scatter through
        the table (a span may straddle blocks — each position resolves
        its own (block, offset)), the slot's table covers the full
        P + max_new + k overshoot from admission, and rollback stays pure
        position bookkeeping — rejected positions become stale entries in
        blocks the slot still owns, overwritten write-before-attend next
        round, never blocks another slot could hold. Token-exact vs the
        dense spec server AND the plain servers (greedy), differential-
        tested in tests/test_kvcache.py."""
        from torchkafka_tpu.ops.kvattn import block_table_attention

        cfg, dcfg, k = self._cfg, self._draft_cfg, self._k
        B, P = self._slots, self._prompt_len
        max_new = self._max_new
        eos_id = self._eos_id
        bs = self._kv_pages.block_size
        NB = self._kv_pages.num_blocks

        def multi_step_paged(params, mcfg, pool_k, pool_v, table, tokens,
                             pos_b):
            """``spec_decode._multi_step`` over a paged pool: S queries at
            per-row start positions, write-before-attend through the
            block table, per-query causal masks to the live length."""
            b, s = tokens.shape
            x = embed_rows(params["embed"], tokens, mcfg.dtype)
            positions = pos_b[:, None] + jnp.arange(s)[None, :]  # [B, S]

            def body(x, inputs):
                layer, pk, pv = inputs
                q, kk, vv = _project_qkv(x, layer, mcfg)
                q = _rope(q, positions, mcfg.rope_theta)
                kk = _rope(kk, positions, mcfg.rope_theta)
                x, pk, pv = block_table_attention(
                    x, q, kk, vv, pk, pv, table, positions, layer, mcfg
                )
                return x, (pk, pv)

            x, (pool_k, pool_v) = lax.scan(
                body, x, (params["layers"], pool_k, pool_v)
            )
            x = _rms_norm(x, params["ln_f"])
            logits = jnp.einsum(
                "bsd,dv->bsv", x, load_weight(params["lm_head"], mcfg.dtype),
                preferred_element_type=jnp.float32,
            )
            return logits, pool_k, pool_v

        def suffix_prefill(params_pair, t_k, t_v, d_k, d_v, table_row, toks,
                           *, start):
            """Chunked prompt-suffix prefill of BOTH pools for one slot
            (the multi-query step at a fixed start IS a suffix prefill);
            returns the target's last-position logits for token 0."""
            tparams, dparams = params_pair
            pos0 = jnp.full((1,), start, jnp.int32)
            t_logits, t_k, t_v = multi_step_paged(
                tparams, cfg, t_k, t_v, table_row, toks, pos0
            )
            _d, d_k, d_v = multi_step_paged(
                dparams, dcfg, d_k, d_v, table_row, toks, pos0
            )
            return t_logits[:, -1], t_k, t_v, d_k, d_v

        self._paged_suffix_fn = suffix_prefill

        def admit_merge(last_tok, pos, gen, logits, admit_mask, key):
            """Greedy token 0 from the target's logits — identical to the
            dense spec admit's tail (speculative serving is greedy-only,
            so the key goes unused past the shared signature)."""
            tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            last_tok = jnp.where(admit_mask, tok0, last_tok)
            pos = jnp.where(admit_mask, P, pos)
            gen = jnp.where(admit_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(admit_mask, tok0, gen[:, 0]))
            return last_tok, pos, gen

        self._paged_merge = jax.jit(admit_merge)

        K = self._ticks_per_sync

        def tick_block(params_pair, caches, last_tok, pos, gen, active_in,
                       key):
            """The dense spec tick over paged pools (same round structure
            and accept/emit bookkeeping — see the dense body's comments);
            the table rides through the donated state unchanged."""
            tparams, dparams = params_pair
            t_k, t_v, d_k, d_v, table, acc, prop, rounds = caches

            def one(carry, _):
                (t_k, t_v, d_k, d_v, acc, prop, rounds, last_tok, pos, gen,
                 done_latch, n_out) = carry
                act = active_in & ~done_latch

                def dbody(c, j):
                    (dk, dv), tok = c
                    logits, dk, dv = multi_step_paged(
                        dparams, dcfg, dk, dv, table, tok[:, None], pos + j
                    )
                    nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                    return ((dk, dv), nxt), nxt

                ((d_k, d_v), _), d_toks = lax.scan(
                    dbody, ((d_k, d_v), last_tok), jnp.arange(k + 1)
                )
                d = jnp.transpose(d_toks[:k])  # [B, k]

                v_in = jnp.concatenate([last_tok[:, None], d], axis=1)
                t_logits, t_k, t_v = multi_step_paged(
                    tparams, cfg, t_k, t_v, table, v_in, pos
                )
                tga = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

                match = tga[:, :k] == d
                n_acc = jnp.sum(
                    jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
                )
                corr = jnp.take_along_axis(tga, n_acc[:, None], axis=1)[:, 0]

                emitted_before = pos - P + 1
                rem = max_new - emitted_before
                idxbuf = jnp.arange(max_new)[None, :]
                alive = act
                n_emit = jnp.zeros_like(pos)
                new_last = last_tok
                eos_hit = jnp.zeros_like(act)
                for j in range(k + 1):
                    tok_j = d[:, j] if j < k else corr
                    tok_j = jnp.where(j < n_acc, tok_j, corr)
                    emit = alive & (j <= n_acc) & (j < rem)
                    sel = (
                        idxbuf == (emitted_before + j)[:, None]
                    ) & emit[:, None]
                    gen = jnp.where(sel, tok_j[:, None], gen)
                    n_emit = n_emit + emit.astype(jnp.int32)
                    new_last = jnp.where(emit, tok_j, new_last)
                    if eos_id is not None:
                        hit = emit & (tok_j == eos_id)
                        eos_hit = eos_hit | hit
                        alive = alive & ~hit
                emitted_after = emitted_before + n_emit
                done_now = act & (eos_hit | (emitted_after >= max_new))
                n_out = jnp.where(done_now, emitted_after, n_out)
                pos = jnp.where(act & ~done_now, pos + n_emit, pos)
                last_tok = jnp.where(act, new_last, last_tok)

                n_act = jnp.sum(act.astype(jnp.int32))
                acc = acc + jnp.sum(jnp.where(act, n_acc, 0))
                prop = prop + k * n_act
                rounds = rounds + (n_act > 0).astype(jnp.int32)
                done_latch = done_latch | done_now
                return (
                    t_k, t_v, d_k, d_v, acc, prop, rounds, last_tok, pos,
                    gen, done_latch, n_out,
                ), None

            done0 = jnp.zeros((B,), bool)
            n0 = jnp.zeros((B,), jnp.int32)
            (t_k, t_v, d_k, d_v, acc, prop, rounds, last_tok, pos, gen,
             done, n_out), _ = lax.scan(
                one,
                (t_k, t_v, d_k, d_v, acc, prop, rounds, last_tok, pos, gen,
                 done0, n0),
                None, length=K,
            )
            return (
                (t_k, t_v, d_k, d_v, table, acc, prop, rounds),
                last_tok, pos, gen, done, n_out,
            )

        def tick_chunk_block(params_pair, caches, last_tok, pos, gen,
                             active_in, key, ctok, ctable, cpos,
                             fin_mask, fin_row):
            """The chunked tick, spec flavor: the SAME jitted program
            first pushes this tick's prefill chunk through BOTH models'
            block pools (each chunk row one suffix token of a
            reserved-but-prefilling slot, writing through its own table
            row — ``multi_step_paged`` with S=1 rows IS the chunk
            stage), then runs the K speculative rounds over the active
            slots. One dispatch per tick, O(1) compiled programs across
            any suffix-length mix — the per-(suffix, start) jit zoo is
            gone for spec serving too. Unlike the plain server's fused
            pass the chunk stage is a separate layer sweep per model
            (the verify's multi-query structure doesn't concatenate with
            S=1 chunk rows); the dispatch-count win is identical, the
            weight-stream sharing is plain-mode only. Activation rides
            the dispatch too: ``fin_mask``/``fin_row`` mark slots whose
            last suffix token landed this tick — token 0 is the
            TARGET's argmax at that chunk row (greedy, like every spec
            admission) and the slot state merges in, ready to join the
            NEXT dispatch's rounds."""
            tparams, dparams = params_pair
            t_k, t_v, d_k, d_v, table, acc, prop, rounds = caches
            t_logits_c, t_k, t_v = multi_step_paged(
                tparams, cfg, t_k, t_v, ctable, ctok[:, None], cpos
            )
            _dl, d_k, d_v = multi_step_paged(
                dparams, dcfg, d_k, d_v, ctable, ctok[:, None], cpos
            )
            chunk_logits = t_logits_c[:, -1]  # [C, V]
            caches, last_tok, pos, gen, done, n_out = tick_block(
                params_pair,
                (t_k, t_v, d_k, d_v, table, acc, prop, rounds),
                last_tok, pos, gen, active_in, key,
            )
            tok0 = jnp.argmax(chunk_logits[fin_row], axis=-1).astype(
                jnp.int32
            )
            last_tok = jnp.where(fin_mask, tok0, last_tok)
            pos = jnp.where(fin_mask, P, pos)
            gen = jnp.where(fin_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(fin_mask, tok0, gen[:, 0]))
            return caches, last_tok, pos, gen, done, n_out

        _tick = jax.jit(tick_block, donate_argnums=(1,))
        self._tick_jit = _tick
        self._tick_fn = lambda *a: _tick(
            (self._params, self._draft_params), *a
        )
        if self._chunked:
            _tick_chunk = jax.jit(tick_chunk_block, donate_argnums=(1,))
            self._tick_chunk_jit = _tick_chunk
            self._tick_chunk_fn = lambda *a: _tick_chunk(
                (self._params, self._draft_params), *a
            )
        else:
            self._tick_chunk_fn = None
        self._tick_block_raw = (
            lambda params, *a: tick_block((params, self._draft_params), *a)
        )
        self._admit_fn = None  # paged admission is host-orchestrated
        self._resume_exec = None  # paged resume rides the chunk/suffix path
        self._paged_table_idx = 4

        nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        dl, dkh, ddh = dcfg.n_layers, dcfg.n_kv_heads, dcfg.head_dim
        self._caches = (
            jnp.zeros((nl, NB, bs, kh, dh), cfg.dtype),
            jnp.zeros((nl, NB, bs, kh, dh), cfg.dtype),
            jnp.zeros((dl, NB, bs, dkh, ddh), dcfg.dtype),
            jnp.zeros((dl, NB, bs, dkh, ddh), dcfg.dtype),
            # .copy(): jnp.asarray may zero-copy an aligned host buffer
            # (CPU backend) and _table_np is mutated in place at
            # admission — snapshot, never a live view.
            jnp.asarray(self._table_np.copy()),
            # accepted / proposed / rounds — distinct buffers (donated
            # tuple; one buffer donated thrice is an XLA error).
            jnp.zeros((), jnp.int32).copy(),
            jnp.zeros((), jnp.int32).copy(),
            jnp.zeros((), jnp.int32).copy(),
        )
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._gen = jnp.zeros((B, max_new), jnp.int32)

    def _paged_prefill_call(self, caches, table_row, toks, *,
                            total_len: int | None = None):
        """Both models' pools prefilled per record; counters/table pass
        through untouched. ``total_len``: full sequence length — a
        journal warm resume prefills prompt + emitted tokens (base-class
        semantics)."""
        s = int(toks.shape[1])
        start = (total_len or self._prompt_len) - s
        fn = self._paged_prefill_jits.get((s, start))
        if fn is None:
            fn = jax.jit(
                functools.partial(self._paged_suffix_fn, start=start),
                donate_argnums=(1, 2, 3, 4),
            )
            self._paged_prefill_jits[(s, start)] = fn
        with xprof.span(xprof.SPAN_ADMIT):
            logits, t_k, t_v, d_k, d_v = fn(
                (self._params, self._draft_params), *caches[:4], table_row,
                toks,
            )
        return logits, (t_k, t_v, d_k, d_v) + caches[4:]

    def swap_draft_params(self, draft_params, draft_cfg=None) -> None:
        """Hot-swap the DRAFT weights in place — the rollout plane's
        delivery path for continuously-distilled drafts (ROADMAP item 1).
        Cheaper contract than ``swap_params``: the draft only PROPOSES —
        verification against the target is what commits tokens — so a
        draft refresh never changes committed output, only the realized
        acceptance α. It can therefore land between ticks without
        quiescing. The jitted programs close over ``self._draft_params``
        at call time; same structure/shapes required (the compiled
        programs are shape-specialized), which ``draft_cfg`` (when given)
        and the tree check enforce."""
        if draft_cfg is not None and (
            draft_cfg.n_layers != self._draft_cfg.n_layers
            or draft_cfg.vocab_size != self._draft_cfg.vocab_size
            or draft_cfg.d_model != self._draft_cfg.d_model
        ):
            raise ValueError(
                "swap_draft_params requires a structurally identical "
                "draft (the compiled rounds are shape-specialized); "
                "rebuild the generator for a different draft geometry"
            )
        old = jax.tree_util.tree_structure(self._draft_params)
        new = jax.tree_util.tree_structure(draft_params)
        if old != new:
            raise ValueError(
                f"draft tree structure mismatch: {new} != {old}"
            )
        if self._mesh is not None:
            from torchkafka_tpu.models.generate import serving_shardings

            draft_params = jax.device_put(
                draft_params,
                serving_shardings(self._draft_cfg, self._mesh, draft_params),
            )
        # Death HERE (candidate fetched + validated, not yet bound) must
        # be invisible in committed output: the incumbent draft still
        # proposes on restart, and either draft yields the target's
        # greedy tokens — the crash matrix pins exactly that.
        crash_hook("draft_swap_pre_apply")
        self._draft_params = draft_params

    def spec_stats(self) -> dict:
        """Measured speculation counters since construction (one device
        fetch). ``acceptance`` is the realized α — the workload-dependent
        number the i.i.d. speedup curve must be evaluated at. Warmup's
        all-inactive rounds don't count (no active slot → no proposals);
        a ``decode_roofline`` probe DOES run live rounds, so measure α
        from a server that hasn't probed (the harness probes a separate
        instance)."""
        # Counters are the state tuple's TAIL in both layouts (dense:
        # pools + 3 counters; paged: pools + table + 3 counters).
        acc, prop, rounds = (
            int(jax.device_get(x)) for x in self._caches[-3:]
        )
        return {
            "rounds": rounds,
            "proposed": prop,
            "accepted": acc,
            "acceptance": round(acc / prop, 4) if prop else None,
            "k": self._k,
            "draft_layers": self._draft_cfg.n_layers,
        }

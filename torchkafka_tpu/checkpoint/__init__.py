"""Checkpoint/resume: train state and stream position, atomically paired."""

from torchkafka_tpu.checkpoint.manager import StreamCheckpointer

__all__ = ["StreamCheckpointer"]

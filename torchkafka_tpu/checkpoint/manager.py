"""Atomic (train-state, stream-offset) checkpointing.

The reference's resume story is "committed Kafka offsets ARE the state"
(SURVEY.md §5 checkpoint row: restart with the same group_id ⇒ resume at the
last commit, /root/reference/README.md:92-96) — sufficient when the consumer
is stateless. A training consumer is not: its model/optimizer state must
advance in lockstep with the stream position, or a restart replays records
into a newer model (or skips records an older model never saw).

``StreamCheckpointer`` fixes the pairing the way SURVEY.md §5 prescribes:
every checkpoint atomically contains BOTH the train-state pytree (Orbax,
which writes tmp-then-rename, so a torn save is invisible) AND the offset
watermark of exactly the batches included in that state (the CommitToken's
offsets). ``restore`` hands both back; ``resume`` additionally seeks the
consumer so the stream continues from the checkpoint — even if the Kafka
group's committed offsets ran ahead (a later commit happened, then the host
died before saving) or behind (checkpoint saved, commit failed). Either way,
state and stream agree afterwards; with commits also barrier-gated, the loss
window is zero and the duplicate window is at most the batches between the
checkpoint and the crash (at-least-once, same contract as the reference).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Mapping

import jax
import numpy as np

from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source.consumer import Consumer
from torchkafka_tpu.source.records import TopicPartition

logger = logging.getLogger(__name__)

_OFFSETS_FILE = "stream_offsets.json"


def _offsets_file(pid: int, multi: bool) -> str:
    """Single-process keeps the historical name; each pod process writes its
    own file (every host owns different partitions)."""
    return f"stream_offsets_{pid}.json" if multi else _OFFSETS_FILE


def _offsets_files(path: str) -> list[str]:
    """Every offsets file in a checkpoint dir — the single-process file
    and/or one per pod process. Restore merges ALL of them: partitions are
    disjoint across processes at save time, and the union is the pod-global
    watermark, which is what makes resuming at a DIFFERENT process count
    (elastic rescale) correct — a new process's assignment may include
    partitions a different old process checkpointed."""
    try:
        names = os.listdir(path)
    except FileNotFoundError:
        return []
    return sorted(
        os.path.join(path, n)
        for n in names
        if n == _OFFSETS_FILE
        or (n.startswith("stream_offsets_") and n.endswith(".json"))
    )


def _read_offsets_metas(path: str) -> list[dict]:
    """Parse every offsets file in a checkpoint dir. A single corrupt or
    oddly-named file marks THIS dir damaged (it is excluded from
    auto-selection via ``_pod_complete``) instead of raising — ``steps()``
    scans every checkpoint, so one torn write must not brick discovery and
    GC of all the healthy ones (ADVICE r2)."""
    metas = []
    for offsets_path in _offsets_files(path):
        try:
            with open(offsets_path) as f:
                meta = json.load(f)
            if not isinstance(meta, dict):
                raise ValueError(f"offsets file is not a JSON object: {meta!r}")
            if "process_index" not in meta:
                # Pre-metadata files: recover the index from the filename.
                name = os.path.basename(offsets_path)
                if name != _OFFSETS_FILE:
                    meta["process_index"] = int(
                        name[len("stream_offsets_"):-len(".json")]
                    )
        except (OSError, ValueError) as exc:  # json.JSONDecodeError ⊂ ValueError
            logger.warning(
                "skipping damaged offsets file %s: %s", offsets_path, exc
            )
            return [{"damaged": True}]
        metas.append(meta)
    return metas


def _pod_complete(metas: list[dict]) -> bool:
    """A pod save of N processes is complete when all N distinct
    per-process files are present. File COUNT is not enough: a stale
    single-process file alongside N-1 per-process files would count to N
    while a partition's watermark is silently missing."""
    if any(m.get("damaged") for m in metas):
        return False
    pod = [m for m in metas if int(m.get("process_count", 1)) > 1]
    if not pod:
        return bool(metas)
    saved_count = max(int(m["process_count"]) for m in pod)
    indexes = {int(m["process_index"]) for m in pod if "process_index" in m}
    return len(indexes) >= saved_count


def _encode_offsets(offsets: Mapping[TopicPartition, int]) -> dict[str, int]:
    return {f"{tp.topic}\x00{tp.partition}": int(off) for tp, off in offsets.items()}


def _decode_offsets(raw: Mapping[str, int]) -> dict[TopicPartition, int]:
    out: dict[TopicPartition, int] = {}
    for key, off in raw.items():
        topic, _, part = key.rpartition("\x00")
        out[TopicPartition(topic, int(part))] = int(off)
    return out


class StreamCheckpointer:
    """Orbax-backed checkpoints of (state pytree, offset watermark).

    Layout: ``<root>/<step>/state`` (Orbax PyTree) + ``<root>/<step>/stream_offsets.json``,
    committed by a final atomic rename of the step directory — a crash
    mid-save leaves only a ``.tmp`` directory that ``latest_step`` ignores.
    """

    def __init__(self, root: str | os.PathLike, *, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self._root = os.path.abspath(os.fspath(root))
        os.makedirs(self._root, exist_ok=True)
        self._keep = keep
        self._ckptr = ocp.StandardCheckpointer()
        self._pending = None  # in-flight save_async finalizer thread
        self._pending_error: BaseException | None = None

    # ------------------------------------------------------------------ save

    def save(
        self,
        step: int,
        state: Any,
        offsets: Mapping[TopicPartition, int],
    ) -> str:
        """Persist ``state`` + ``offsets`` as checkpoint ``step``.

        ``offsets`` is normally ``token.offsets`` of the LAST batch folded
        into ``state`` — i.e. commit watermark and weights describe the same
        records.
        """
        # The caller has typically just committed the offsets this save
        # pairs with: death between that commit and this save means the
        # checkpoint on disk is OLDER than the commit log — resume must
        # seek back to the checkpoint's watermark (re-consuming, never
        # losing). The crash matrix kills here to pin that.
        crash_hook("post_commit_pre_checkpoint")
        self.wait_until_finished()  # serialize after any async save
        final = os.path.join(self._root, str(step))
        tmp = final + ".tmp"
        multi = jax.process_count() > 1
        pid = jax.process_index()
        if pid == 0 and os.path.exists(tmp):
            import shutil

            shutil.rmtree(tmp)
        if multi:
            # Pod save: state arrays stay jax.Arrays (Orbax coordinates the
            # sharded multi-host write; np.asarray of a non-addressable
            # global array would throw); every process calls save on the
            # SAME path, process 0 performs the commit rename, and
            # barriers order prepare → write → rename. Host-local leaves
            # (per-host scalars/metrics, SingleDeviceSharding) are rejected
            # by Orbax multi-host serialization — promote them to globally
            # replicated arrays first (they are identical across hosts by
            # the time they reach a checkpoint).
            from jax.experimental import multihost_utils as _mh
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(
                jax.sharding.Mesh(np.array(jax.devices()), ("all",)),
                PartitionSpec(),
            )

            def _globalize(x):
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return x  # already a proper global array
                arr = np.asarray(x)
                return jax.make_array_from_callback(
                    arr.shape, rep, lambda idx: arr[idx]
                )

            state = jax.tree_util.tree_map(_globalize, state)
            _mh.sync_global_devices(f"ckpt-prepare-{step}")
            self._ckptr.save(os.path.join(tmp, "state"), state)
        else:
            state = jax.tree_util.tree_map(np.asarray, state)  # device → host
            self._ckptr.save(os.path.join(tmp, "state"), state)
        self._ckptr.wait_until_finished()
        self._write_offsets(tmp, pid, multi, step, offsets)
        # Payload and offsets written, the atomic rename NOT yet done:
        # death here leaves a ``.tmp`` step that steps()/restore must
        # never see (restore(step=None) falls back to the newest
        # COMPLETE step).
        crash_hook("checkpoint_mid_write")
        if multi:
            from jax.experimental import multihost_utils as _mh

            _mh.sync_global_devices(f"ckpt-written-{step}")
        if pid == 0:
            self._commit_rename(tmp, final)
        if multi:
            from jax.experimental import multihost_utils as _mh

            _mh.sync_global_devices(f"ckpt-renamed-{step}")
        logger.info("checkpoint %d saved (%d partitions)", step, len(offsets))
        return final

    def save_async(
        self,
        step: int,
        state: Any,
        offsets: Mapping[TopicPartition, int],
    ) -> None:
        """Non-blocking ``save``: dispatch the Orbax write and return; a
        finalizer thread performs the atomic rename once the write lands.
        The training loop keeps stepping while the checkpoint drains —
        Orbax snapshots device arrays to host before returning from its
        (async) ``save``, so later parameter updates cannot tear the
        checkpoint.

        Serialization: a second ``save_async`` (or ``save``) first waits
        for the previous one, so checkpoints commit in step order. Call
        ``wait_until_finished()`` before reading ``steps()``/``restore()``
        if you need the async save visible. On a pod this falls back to
        the synchronous path: the rename barriers must interleave
        identically on every host, which a background thread racing the
        main thread's commit barriers cannot guarantee."""
        if jax.process_count() > 1:
            self.save(step, state, offsets)
            return
        self.wait_until_finished()
        final = os.path.join(self._root, str(step))
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            import shutil

            shutil.rmtree(tmp)
        # Copy only host-resident leaves: np.asarray on an already-host
        # array is a view, and the caller's next in-place update would
        # tear the still-draining write. jax.Arrays are snapshotted by
        # Orbax's own async D2H copy — no need to block on them here.
        state = jax.tree_util.tree_map(
            lambda x: x if isinstance(x, jax.Array) else np.array(x), state
        )
        self._ckptr.save(os.path.join(tmp, "state"), state)
        self._write_offsets(tmp, 0, False, step, offsets)
        # Same torn window as the sync path: everything written, rename
        # pending (here on the finalizer thread).
        crash_hook("checkpoint_mid_write")

        def _finalize() -> None:
            try:
                self._ckptr.wait_until_finished()
                self._commit_rename(tmp, final)
                logger.info("async checkpoint %d committed", step)
            except BaseException as e:  # noqa: BLE001 - re-raised on join
                self._pending_error = e

        import threading

        self._pending = threading.Thread(
            target=_finalize, name=f"ckpt-finalize-{step}", daemon=True
        )
        self._pending.start()

    def wait_until_finished(self) -> None:
        """Block until any in-flight ``save_async`` has fully committed.
        Re-raises the finalizer's failure — a checkpoint that failed to
        commit must not look durable."""
        pending = getattr(self, "_pending", None)
        if pending is not None:
            pending.join()
            self._pending = None
        err = getattr(self, "_pending_error", None)
        if err is not None:
            self._pending_error = None
            raise RuntimeError("async checkpoint failed to commit") from err

    def _write_offsets(
        self,
        tmp: str,
        pid: int,
        multi: bool,
        step: int,
        offsets: Mapping[TopicPartition, int],
    ) -> None:
        # The tmp dir normally exists because the orbax save targeted
        # tmp/state — but that is orbax's internal layout, not a
        # contract, and AsyncCheckpointer has been observed (under a
        # loaded suite) to defer materialising it past this point.
        # Create it explicitly; exist_ok covers the normal case.
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, _offsets_file(pid, multi)), "w") as f:
            json.dump(
                {
                    "step": step,
                    "process_index": pid,
                    "process_count": jax.process_count(),
                    "offsets": _encode_offsets(offsets),
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())

    def _commit_rename(self, tmp: str, final: str) -> None:
        if os.path.exists(final):
            import shutil

            shutil.rmtree(final)
        os.rename(tmp, final)  # the atomic commit point
        self._gc()

    def _gc(self) -> None:
        """Prune every checkpoint dir older than the keep-th newest COMPLETE
        step — including damaged/incomplete dirs (excluded from ``steps()``,
        they would otherwise leak their Orbax state payloads forever). A
        damaged dir NEWER than the kept floor survives for forensics until
        newer complete saves age it out. Deleting an aged-out damaged dir is
        the same retention policy as for healthy ones: had its offsets file
        been intact, age-based GC would prune the dir at this point anyway,
        and ``keep`` newer complete checkpoints exist by construction —
        GC runs ONLY once that many complete steps exist (ADVICE r3: the
        early regime used the oldest complete step as the floor, pruning
        forensic dirs sooner than this docstring promised)."""
        if not self._keep:
            return
        steps = self.steps()
        if len(steps) < self._keep:
            return
        keep_floor = steps[-self._keep]
        import shutil

        for name in os.listdir(self._root):
            if name.isdigit() and int(name) < keep_floor:
                shutil.rmtree(os.path.join(self._root, name), ignore_errors=True)

    # --------------------------------------------------------------- restore

    def steps(self) -> list[int]:
        """Steps with COMPLETE offsets state. An incomplete pod checkpoint
        (a per-process file lost in a copy/prune) is excluded, so
        auto-selection (``restore(step=None)``) falls back to the newest
        restorable checkpoint instead of bricking resume; restoring an
        incomplete step EXPLICITLY still fails loudly in ``restore``."""
        out = []
        for name in os.listdir(self._root):
            if name.isdigit() and _pod_complete(
                _read_offsets_metas(os.path.join(self._root, name))
            ):
                out.append(int(name))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(
        self, step: int | None = None, *, template: Any | None = None
    ) -> tuple[Any, dict[TopicPartition, int], int]:
        """→ (state, offsets, step). ``template``: a pytree with the target
        structure/dtypes (e.g. abstract arrays) for Orbax to restore into.

        ``offsets`` is the POD-GLOBAL watermark: the union of every
        process's offsets file in the checkpoint. Partitions are disjoint
        across processes at save time, so the union is exact; merging (not
        picking the caller's own file) is what makes restoring at a
        different process count — elastic rescale — correct, since the new
        assignment need not match the old one. On the off chance two files
        overlap on a partition (a save written twice across a topology
        change), the SMALLER watermark wins: seeking too far forward would
        skip records, while re-delivery is the at-least-once contract."""
        self.wait_until_finished()  # make any in-flight async save visible
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._root}")
        path = os.path.join(self._root, str(step))
        # Validate the offsets state BEFORE the (potentially minutes-long)
        # Orbax state restore, and distinguish torn files from lost ones so
        # the operator chases the right failure.
        metas = _read_offsets_metas(path)
        if not metas:
            raise FileNotFoundError(f"no offsets file in {path}")
        if any(m.get("damaged") for m in metas):
            raise FileNotFoundError(
                f"damaged checkpoint in {path}: an offsets file exists but "
                "failed to parse (torn write?) — see the logged warning"
            )
        if not _pod_complete(metas):
            # An incomplete pod checkpoint (a per-process file lost in a
            # copy/prune) would restore a PARTIAL watermark: the missing
            # partitions silently fall back to the group's committed
            # offsets, which may be ahead — skipping records the restored
            # state never saw. Fail loudly instead.
            raise FileNotFoundError(
                f"incomplete pod checkpoint in {path}: missing per-process "
                "offsets files for the recorded process_count"
            )
        state = self._ckptr.restore(
            os.path.join(path, "state"), template if template is not None else None
        )
        merged: dict[TopicPartition, int] = {}
        for meta in metas:
            for tp, off in _decode_offsets(meta["offsets"]).items():
                merged[tp] = min(off, merged.get(tp, off))
        return state, merged, step

    def resume(
        self,
        consumer: Consumer,
        step: int | None = None,
        *,
        template: Any | None = None,
    ) -> tuple[Any, int]:
        """Restore AND align the consumer: seek every checkpointed partition
        this process is assigned to its saved watermark, so the next poll
        continues exactly where the restored state left off (regardless of
        the group's committed offsets). → (state, step).

        The restored watermark is pod-global (see ``restore``), so this
        works across rescales: each process of the NEW topology seeks the
        subset of partitions it now owns, whichever old process saved them.
        Partitions owned by peers are skipped silently on a pod; on a
        single process they are real orphans and warn."""
        state, offsets, step = self.restore(step, template=template)
        assigned = set(consumer.assignment())
        elsewhere = 0
        for tp, off in offsets.items():
            if tp in assigned:
                consumer.seek(tp, off)
            elif jax.process_count() > 1:
                elsewhere += 1
            else:
                logger.warning(
                    "checkpointed partition %s not in current assignment; "
                    "its owner must resume it", tp,
                )
        if elsewhere:
            logger.info(
                "%d checkpointed partitions assigned to peer processes", elsewhere
            )
        return state, step

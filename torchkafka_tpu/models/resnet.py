"""ResNet-50, inference-first, pure JAX — BASELINE config 4's consumer.

Net-new vs the reference (no model code in its tree, SURVEY.md §2). Written
for the MXU: NHWC layout (the TPU-native conv layout), bfloat16 compute, and
inference-mode batch norm folded into a single scale-and-shift per channel so
XLA fuses it into the adjacent convolution.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Bottleneck stage layout for ResNet-50: (blocks, mid_channels, stride).
_STAGES = ((3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2))


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), dtype) * math.sqrt(2.0 / fan_in)
    return w


def _bn_init(c, dtype):
    # Inference-mode BN folded to scale/shift (identity at init).
    return {"scale": jnp.ones((c,), dtype), "shift": jnp.zeros((c,), dtype)}


def init_params(rng: jax.Array, cfg: ResNetConfig = ResNetConfig()) -> dict:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, 64))
    params: dict = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width, pd), "bn": _bn_init(cfg.width, pd)}
    }
    cin = cfg.width
    for s, (blocks, mid, stride) in enumerate(_STAGES):
        stage = []
        cout = mid * 4
        for b in range(blocks):
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, pd),
                "bn1": _bn_init(mid, pd),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, pd),
                "bn2": _bn_init(mid, pd),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, pd),
                "bn3": _bn_init(cout, pd),
            }
            if b == 0:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                blk["bn_proj"] = _bn_init(cout, pd)
            stage.append(blk)
            cin = cout
        params[f"stage{s}"] = stage
    params["fc"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), pd) / math.sqrt(cin),
        "b": jnp.zeros((cfg.num_classes,), pd),
    }
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return lax.conv_general_dilated(
        x.astype(dtype),
        w.astype(dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )


def _bn(x, bn, dtype=jnp.bfloat16):
    return (x * bn["scale"].astype(jnp.float32) + bn["shift"].astype(jnp.float32)).astype(dtype)


def _bottleneck(x, blk, stride, dtype):
    out = jax.nn.relu(_bn(_conv(x, blk["conv1"], 1, dtype), blk["bn1"], dtype))
    out = jax.nn.relu(_bn(_conv(out, blk["conv2"], stride, dtype), blk["bn2"], dtype))
    out = _bn(_conv(out, blk["conv3"], 1, dtype), blk["bn3"], dtype)
    if "proj" in blk:
        x = _bn(_conv(x, blk["proj"], stride, dtype), blk["bn_proj"], dtype)
    return jax.nn.relu(out + x)


def forward(params: dict, images: jax.Array, cfg: ResNetConfig = ResNetConfig()) -> jax.Array:
    """images: [B, H, W, 3] float (already normalized) → logits [B, classes]."""
    dt = cfg.dtype
    x = jax.nn.relu(_bn(_conv(images, params["stem"]["conv"], 2, dt), params["stem"]["bn"], dt))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for s, (blocks, _mid, stride) in enumerate(_STAGES):
        for b in range(blocks):
            x = _bottleneck(x, params[f"stage{s}"][b], stride if b == 0 else 1, dt)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ params["fc"]["w"].astype(jnp.float32) + params["fc"]["b"].astype(jnp.float32)


def preprocess(raw_uint8: jax.Array, out_size: int = 224) -> jax.Array:
    """On-device decode tail for ingested [B, h, w, 3] uint8 frames: resize to
    [B, out, out, 3] and normalize. Runs inside the consumer's jit step so the
    host ships compact uint8 and the TPU does the pixel math."""
    x = raw_uint8.astype(jnp.float32) / 255.0
    b, h, w, c = x.shape
    x = jax.image.resize(x, (b, out_size, out_size, c), method="bilinear")
    mean = jnp.asarray([0.485, 0.456, 0.406])
    std = jnp.asarray([0.229, 0.224, 0.225])
    return (x - mean) / std


def count_params(params: dict) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))

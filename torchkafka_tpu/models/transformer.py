"""Llama-style decoder-only transformer, TPU-first.

Net-new vs the reference (no model code in its tree — SURVEY.md §2); this is
the flagship consumer of the ingest pipeline for BASELINE configs 3 and 5.

Design choices, all for the TPU/XLA compilation model:

- **Pure pytree params, stacked layers.** Parameters are a plain dict with
  every per-layer tensor stacked on a leading [L, ...] axis, and the forward
  pass runs ``lax.scan`` over that axis: one traced layer body, compile time
  independent of depth, and a single PartitionSpec per tensor covers all
  layers.
- **bfloat16 compute, float32 params/accumulators.** Matmuls hit the MXU in
  bf16 (``cfg.dtype``); master weights, optimizer moments, softmax and the
  online-attention recurrence stay f32.
- **Sharding by spec, collectives by XLA.** ``param_specs`` gives each tensor
  a PartitionSpec over a {data, fsdp, tp, sp} mesh (2D "megatron" TP for
  attention/MLP, fsdp sharding on the other matmul dim, replicated norms).
  The train step is one ``jax.jit`` whose in/out shardings are those specs —
  XLA inserts all_gather/reduce_scatter/psum where the math demands them.
  No hand-written collectives outside ring attention's explicit ppermute.
- **Sequence parallelism is real.** With an ``sp`` axis of size > 1 the
  activations are sharded over sequence, and attention runs as ring
  attention (torchkafka_tpu.ops.attention) so no device ever materialises
  the full sequence. RoPE/norms/MLP are elementwise-in-sequence and need no
  communication.
- **Remat.** ``cfg.remat`` wraps the scanned layer body in
  ``jax.checkpoint``, trading recompute for HBM — the standard long-context
  lever.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchkafka_tpu.models.quant import QTensor, embed_rows, load_weight
from torchkafka_tpu.ops.attention import mha, ring_attention, ulysses_attention
from torchkafka_tpu.ops.xent import dense_softmax_xent, fused_softmax_xent


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8  # < n_heads → grouped-query attention
    d_ff: int = 1376
    max_seq_len: int = 512
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16  # compute dtype (MXU)
    param_dtype: Any = jnp.float32  # master weights
    remat: bool = False
    # 'dense' | 'flash' | 'ring' | 'ulysses' | 'auto': auto picks ring when
    # the mesh has sp>1 (no head-divisibility constraint), else the Pallas
    # flash kernel on TPU, else dense XLA. 'ulysses' selects all-to-all
    # sequence parallelism (heads must divide by the sp size).
    attn_impl: str = "auto"
    # Sequence-parallel attention over the Pallas flash kernels — governs
    # BOTH 'ring' (per ring step) and 'ulysses' (per head-shard): None =
    # on TPU when the shard tiles; True forces (tests/dryruns exercise the
    # kernels in interpret mode off-TPU); False forces the dense body.
    ring_use_flash: bool | None = None
    # Mixture-of-experts MLP: 0 = dense SwiGLU; >0 = that many experts with
    # top-k routing, expert weights sharded over the mesh's 'ep' axis.
    n_experts: int = 0
    expert_top_k: int = 2
    router_aux_coef: float = 0.01  # load-balance loss weight (0 disables)
    # 'dense': exact one-hot combine, every ep shard computes all tokens
    # for its local experts (no drops, E/ep-fold compute). 'capacity':
    # Switch-style dispatch — each expert takes at most
    # ceil(group·k/E · capacity_factor) tokens PER TOKEN GROUP, overflow
    # drops, per-shard compute scales down E/ep-fold (the pod-scale path).
    # TRAINING-ONLY knob: the KV-cache decode path (models/generate.py,
    # serve.py) always routes exactly — capacity drops are a training
    # throughput/regularization tradeoff, and decode-sized batches fit
    # under any capacity anyway (standard MoE serving semantics).
    moe_dispatch: str = "dense"
    capacity_factor: float = 1.25
    # Tokens dispatch within groups of exactly this size (the tail group is
    # padded with masked rows, so ANY token count — including primes —
    # keeps full groups). The one-hot dispatch einsum costs n_g·E·C·D per
    # group; ungrouped (n_g = all tokens) it grows QUADRATIC in tokens and
    # dwarfs the expert MLP itself (measured 20x at 16k tokens); 256 keeps
    # it a fraction of MLP cost.
    moe_group_size: int = 256
    # Pipeline parallelism: with a 'pp' mesh axis of size > 1 the layer
    # stack runs as a GPipe schedule (ops/pipeline.py) with this many
    # microbatches (None = pipeline depth). The router aux loss IS
    # collected under pp: per-microbatch routing statistics accumulate
    # through the schedule and psum across stages into exactly the
    # full-batch statistic (see ``router_aux``).
    pp_microbatches: int | None = None
    # Fused blocked cross-entropy (ops/xent.py): None = auto block size,
    # >0 = that sequence block, 0 = disable (always full-logits dense CE).
    # Auto-disabled under sp>1 meshes and quantized heads either way.
    ce_block_size: int | None = None
    # Unroll factor for the lax.scan over the stacked layers. None = auto:
    # fully unroll stacks of ≤ 8 layers (XLA schedules the unrolled trunk
    # ~15% faster on v5e at batch 64; measured in PERF.md), scan deeper
    # stacks (compile time independent of depth — the reason scan is the
    # default structure). 1 = never unroll.
    scan_unroll: int | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    def __post_init__(self) -> None:
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must divide by n_kv_heads")
        if self.n_experts and self.expert_top_k > self.n_experts:
            raise ValueError("expert_top_k cannot exceed n_experts")
        if self.moe_dispatch not in ("dense", "capacity"):
            raise ValueError(
                f"moe_dispatch must be 'dense' or 'capacity', got "
                f"{self.moe_dispatch!r}"
            )
        if self.capacity_factor <= 0:
            raise ValueError("capacity_factor must be positive")
        if self.moe_group_size < 1:
            raise ValueError("moe_group_size must be >= 1")


# --------------------------------------------------------------------- params


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs per tensor, over mesh axes {data, fsdp, tp, sp, ep}.

    Megatron 2D layout: the "output features" dim of up-projections (wq/wk/wv,
    w_gate/w_up) and the vocab dim shard over ``tp``; the opposing dim shards
    over ``fsdp`` (ZeRO-3-style weight sharding that XLA turns into
    all_gathers just-in-time). MoE expert weights add a leading expert dim
    sharded over ``ep``. Mesh axes absent from the actual Mesh are stripped
    by ``shardings_for_mesh``.
    """
    if cfg.is_moe:
        mlp = {
            "router": P(None, "fsdp", None),  # [L, D, E] — replicated over ep
            "w_gate": P(None, "ep", "fsdp", "tp"),  # [L, E, D, F]
            "w_up": P(None, "ep", "fsdp", "tp"),
            "w_down": P(None, "ep", "tp", "fsdp"),  # [L, E, F, D]
        }
    else:
        mlp = {
            "w_gate": P(None, "fsdp", "tp"),  # [L, D, F]
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),  # [L, F, D]
        }
    # The stacked layer dim shards over 'pp' (each pipeline stage owns a
    # contiguous slice of layers); on meshes without pp it strips to None.
    def with_pp(spec: P) -> P:
        return P("pp", *tuple(spec)[1:])

    return {
        "embed": P("tp", "fsdp"),  # [V, D]
        "layers": {
            k: with_pp(v)
            for k, v in {
                "ln1": P(None, None),  # [L, D]
                "ln2": P(None, None),
                "wq": P(None, "fsdp", "tp", None),  # [L, D, H, Dh]
                "wk": P(None, "fsdp", "tp", None),  # [L, D, K, Dh]
                "wv": P(None, "fsdp", "tp", None),
                "wo": P(None, "tp", None, "fsdp"),  # [L, H, Dh, D]
                **mlp,
            }.items()
        },
        "ln_f": P(None),  # [D]
        "lm_head": P("fsdp", "tp"),  # [D, V]
    }


def shardings_for_mesh(mesh: Mesh, specs: Any) -> Any:
    """Convert specs → NamedShardings, dropping axis names the mesh lacks."""

    def fix(spec: P) -> NamedSharding:
        parts = []
        for entry in spec:
            if entry is None:
                parts.append(None)
            elif isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in mesh.shape)
                parts.append(kept if kept else None)
            else:
                parts.append(entry if entry in mesh.shape else None)
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map(
        fix, specs, is_leaf=lambda x: isinstance(x, P)
    )


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Scaled-normal init, stacked [L, ...] per layer tensor."""
    keys = jax.random.split(rng, 10)
    dm, dff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    h, k, dh, v = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.vocab_size
    pd = cfg.param_dtype

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) / math.sqrt(fan_in)).astype(pd)

    if cfg.is_moe:
        ne = cfg.n_experts
        mlp = {
            "router": norm(keys[8], (nl, dm, ne), dm),
            "w_gate": norm(keys[5], (nl, ne, dm, dff), dm),
            "w_up": norm(keys[6], (nl, ne, dm, dff), dm),
            "w_down": norm(keys[7], (nl, ne, dff, dm), dff),
        }
    else:
        mlp = {
            "w_gate": norm(keys[5], (nl, dm, dff), dm),
            "w_up": norm(keys[6], (nl, dm, dff), dm),
            "w_down": norm(keys[7], (nl, dff, dm), dff),
        }
    return {
        "embed": norm(keys[0], (v, dm), dm),
        "layers": {
            "ln1": jnp.ones((nl, dm), pd),
            "ln2": jnp.ones((nl, dm), pd),
            "wq": norm(keys[1], (nl, dm, h, dh), dm),
            "wk": norm(keys[2], (nl, dm, k, dh), dm),
            "wv": norm(keys[3], (nl, dm, k, dh), dm),
            "wo": norm(keys[4], (nl, h, dh, dm), h * dh),
            **mlp,
        },
        "ln_f": jnp.ones((dm,), pd),
        "lm_head": norm(keys[9], (dm, v), dm),
    }


# -------------------------------------------------------------------- forward


def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (xf * rms).astype(x.dtype) * scale.astype(x.dtype)


def router_aux(stats: jax.Array, n_tokens: int | jax.Array) -> jax.Array:
    """Switch load-balance loss from routing sufficient statistics.

    stats: [2, E] f32 — row 0 = Σ_tokens routed-one-hot (how many of the
    token·top-k assignments landed on each expert), row 1 = Σ_tokens router
    softmax prob per expert. aux = E · Σ_e (routed_e/N) · (probs_e/N),
    minimized at top_k when routing is uniform. Keeping token SUMS (not the
    pre-reduced scalar) is what lets pipeline parallelism collect the loss:
    per-microbatch sums add across microbatches/stages/sequence shards into
    exactly the full-batch statistic, where a product-of-means scalar would
    not (mean of products ≠ product of means)."""
    e = stats.shape[-1]
    return e * jnp.sum((stats[0] / n_tokens) * (stats[1] / n_tokens))


def _moe_mlp(
    h: jax.Array, layer: Mapping[str, jax.Array], cfg: "TransformerConfig"
) -> tuple[jax.Array, jax.Array]:
    """Top-k routed mixture of SwiGLU experts, expert dim sharded over the
    mesh's ``ep`` axis. Dense (one-hot combine) dispatch: each ep shard
    computes its local experts for all tokens and the gate-weighted combine
    reduces across ``ep`` (a psum XLA inserts). Exact w.r.t. the routing —
    no capacity-factor token dropping — at the cost of E/ep-fold local MLP
    compute; an all_to_all token-routing dispatch is the scale-up path.
    h: [B, S, D] → (output [B, S, D], router stats [2, E] for
    ``router_aux``)."""
    logits = jnp.einsum(
        "bsd,de->bse", h.astype(jnp.float32), layer["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [B,S,E]
    top_vals, top_idx = lax.top_k(probs, cfg.expert_top_k)  # [B,S,K]
    gates = top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=probs.dtype) * gates[..., None],
        axis=2,
    )  # [B,S,E] — gate weight per (token, expert), 0 if not routed
    gate_e = jax.nn.silu(
        jnp.einsum("bsd,edf->ebsf", h, load_weight(layer["w_gate"], cfg.dtype))
    )
    up_e = jnp.einsum("bsd,edf->ebsf", h, load_weight(layer["w_up"], cfg.dtype))
    out_e = jnp.einsum(
        "ebsf,efd->ebsd", gate_e * up_e, load_weight(layer["w_down"], cfg.dtype)
    )
    out = jnp.einsum("ebsd,bse->bsd", out_e, combine.astype(cfg.dtype))
    # Load-balance sufficient stats: token-summed routed counts and probs.
    routed = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32), axis=2
    )
    stats = jnp.stack([routed.sum(axis=(0, 1)), probs.sum(axis=(0, 1))])
    return out, stats


def moe_capacity(cfg: "TransformerConfig", n_tokens: int) -> int:
    """Per-expert token slots under capacity dispatch: the even share of
    (token, choice) assignments times ``capacity_factor``, padded to a
    multiple of 8 (TPU sublane) with a floor of 8."""
    even = n_tokens * cfg.expert_top_k / cfg.n_experts
    cap = int(math.ceil(even * cfg.capacity_factor))
    return max(8, -(-cap // 8) * 8)


def _moe_mlp_capacity(
    h: jax.Array, layer: Mapping[str, jax.Array], cfg: "TransformerConfig"
) -> tuple[jax.Array, jax.Array]:
    """Capacity-based token dispatch (the scale-up path): tokens are split
    into contiguous groups of ``moe_group_size``; within each group every
    expert accepts at most C = ceil(n_g·k/E · capacity_factor) tokens,
    routed via one-hot dispatch/combine einsums (the Mesh-TensorFlow /
    Switch MoE formulation — einsums, not gathers, so XLA shards the
    [G, E, C, D] expert batches over the mesh's ``ep`` axis and inserts
    the token-exchange collectives itself). Per-ep-shard MLP compute is
    k·cf·tokens/ep slots instead of the dense path's ALL tokens × local
    experts — the E/ep-fold saving the dense docstring calls out. Grouping
    bounds the dispatch einsum at n_g·E·C·D per group; ungrouped it grows
    quadratic in tokens and dominates (measured 20× the MLP at 16k
    tokens).

    Overflow beyond C (an uneven router within a group) is DROPPED,
    Switch-style: the token's k-th choice contributes nothing and its
    residual passes through; primary choices outrank secondary ones (the
    k axis is ordered ahead of the token axis in the position cumsum).
    Exactness: with ``capacity_factor`` high enough for zero drops this
    matches ``_moe_mlp`` to float tolerance (differential-tested)."""
    b, s, d = h.shape
    n = b * s
    e, k = cfg.n_experts, cfg.expert_top_k
    # Contiguous groups of exactly ``moe_group_size`` tokens, the tail group
    # padded with masked rows. Padding (vs the old largest-divisor search)
    # keeps groups full-size for ANY token count: a prime n used to
    # degenerate to 1-token groups, whose per-group capacity floor of 8
    # slots/expert blew the dispatch up 8·E-fold (ADVICE r3).
    n_g = min(cfg.moe_group_size, n)
    g = -(-n // n_g)
    n_pad = g * n_g
    cap = moe_capacity(cfg, n_g)
    x = h.reshape(n, d)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    # 1.0 for real tokens, 0.0 for padding: padded rows claim no capacity
    # slots, combine to zero output, and are excluded from the aux stats.
    valid = (jnp.arange(n_pad) < n).astype(jnp.float32)
    logits = jnp.einsum(
        "nd,de->ne", x.astype(jnp.float32), layer["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [N_pad, E]
    top_vals, top_idx = lax.top_k(probs, k)  # [N_pad, K]
    gates = top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)

    def to_group_major(t: jax.Array) -> jax.Array:
        """[N_pad, K] → [G, K·n_g]: all primary choices outrank all
        secondary ones, tokens in sequence order within a tier."""
        return t.reshape(g, n_g, k).transpose(0, 2, 1).reshape(g, k * n_g)

    idx_g = to_group_major(top_idx)
    valid_g = to_group_major(jnp.broadcast_to(valid[:, None], (n_pad, k)))
    onehot = jax.nn.one_hot(idx_g, e, dtype=jnp.float32) * valid_g[..., None]
    pos = jnp.cumsum(onehot, axis=1) - onehot  # slot within (group, expert)
    keep = onehot * (pos < cap)  # overflow drops
    # dispatch/combine [G, K·n_g, E, C]: one-hot in the slot dim where kept.
    slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
    dispatch = keep[..., None] * slot
    gates_g = to_group_major(gates)
    combine = dispatch * gates_g[..., None, None]

    # Expose the k axis to the einsums instead of tiling activations
    # k-fold (a [K·N, D] copy that would survive into backward): the
    # contraction indexes tokens once and sums k inside the einsum.
    disp5 = dispatch.reshape(g, k, n_g, e, cap).astype(cfg.dtype)
    comb5 = combine.reshape(g, k, n_g, e, cap).astype(cfg.dtype)
    x_g = x.reshape(g, n_g, d)
    expert_in = jnp.einsum(
        "gknec,gnd->gecd", disp5, x_g
    )  # [G, E, C, D] — E ep-sharded; XLA inserts the token exchange
    gate_e = jax.nn.silu(
        jnp.einsum(
            "gecd,edf->gecf", expert_in, load_weight(layer["w_gate"], cfg.dtype)
        )
    )
    up_e = jnp.einsum(
        "gecd,edf->gecf", expert_in, load_weight(layer["w_up"], cfg.dtype)
    )
    out_e = jnp.einsum(
        "gecf,efd->gecd", gate_e * up_e, load_weight(layer["w_down"], cfg.dtype)
    )
    # Combine sums over (k, e, c) in one contraction → [G, n_g, D]; padded
    # rows combine to zero and are sliced off.
    out = jnp.einsum("gknec,gecd->gnd", comb5, out_e)
    out = out.reshape(n_pad, d)[:n].reshape(b, s, d)

    # Same Switch load-balance stats as the dense path (computed on the
    # PRE-capacity routing — the balance loss exists to prevent the very
    # imbalance that causes capacity drops). Padded rows excluded.
    routed = jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=jnp.float32), axis=1
    ) * valid[:, None]  # [N_pad, E]
    stats = jnp.stack(
        [routed.sum(axis=0), (probs * valid[:, None]).sum(axis=0)]
    )
    return out, stats


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B, S, H, D]; positions: [S] global positions
    shared across the batch, or [B, S] per-row positions (the continuous-
    batching server's slots sit at different depths)."""
    dim = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [(B,) S, D/2]
    if angles.ndim == 2:
        angles = angles[None]  # broadcast over batch
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Transformer:
    """Functional model bound to a config (and optionally a mesh for SP)."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh | None = None):
        self.cfg = cfg
        self.mesh = mesh
        sp_size = mesh.shape.get("sp", 1) if mesh is not None else 1
        if cfg.attn_impl in ("ring", "ulysses") and sp_size <= 1:
            # An *explicitly* requested sequence-parallel impl that cannot
            # engage is a misconfigured mesh, not a preference — degrading
            # silently would run without the parallelism the caller asked
            # for (ADVICE r2). 'auto' remains the adaptive spelling.
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} requires a mesh with an 'sp' "
                f"axis of size > 1 (got sp={sp_size}); use attn_impl='auto' "
                "to fall back to flash/dense when sp is absent"
            )
        use_ring = cfg.attn_impl == "ring" or (
            cfg.attn_impl == "auto" and sp_size > 1
        )
        self._use_ring = use_ring and mesh is not None
        self._use_ulysses = cfg.attn_impl == "ulysses"
        self._use_flash = not (self._use_ring or self._use_ulysses) and (
            cfg.attn_impl == "flash"
            or (cfg.attn_impl == "auto" and jax.default_backend() == "tpu")
        )
        # Flash on a multi-device auto-sharded mesh must go through
        # shard_map (a Pallas call is opaque to GSPMD — it cannot split
        # the kernel the way it splits einsums): batch over (data, fsdp),
        # heads over tp, zero collectives. Head counts must divide tp for
        # even shards; otherwise the dense path serves (GSPMD partitions
        # plain einsums fine). Batch divisibility is checked per call.
        self._flash_shard_mesh = None
        if (
            self._use_flash
            and mesh is not None
            # NOT under pipeline parallelism: pp>1 runs the layers inside
            # gpipe's manual-over-pp shard_map region, where a nested
            # shard_map over the full mesh trips a context-mesh mismatch
            # — there the kernel stays plain, as before this gate.
            and mesh.shape.get("pp", 1) == 1
            and any(
                mesh.shape.get(a, 1) > 1 for a in ("data", "fsdp", "tp")
            )
        ):
            tp_sz = mesh.shape.get("tp", 1)
            if cfg.n_heads % tp_sz or cfg.n_kv_heads % tp_sz:
                self._use_flash = False
            else:
                self._flash_shard_mesh = mesh

    def init(self, rng: jax.Array) -> dict:
        return init_params(rng, self.cfg)

    def _attention(self, q, k, v):
        if self._use_ulysses:
            return ulysses_attention(
                q, k, v, mesh=self.mesh, axis_name="sp", causal=True,
                use_flash=self.cfg.ring_use_flash,
            )
        if self._use_ring:
            return ring_attention(
                q, k, v, mesh=self.mesh, axis_name="sp", causal=True,
                use_flash=self.cfg.ring_use_flash,
            )
        if self._use_flash:
            from torchkafka_tpu.ops.flash import (
                flash_attention,
                flash_attention_sharded,
            )

            if self._flash_shard_mesh is not None:
                m = self._flash_shard_mesh
                n_b = m.shape.get("data", 1) * m.shape.get("fsdp", 1)
                if q.shape[0] % n_b == 0:
                    return flash_attention_sharded(q, k, v, m, causal=True)
                # Batch does not split evenly (e.g. a small serving slot
                # pool on a wide mesh): dense body, repeating GQA kv here
                # because the flash path skipped _layer's repeat.
                from torchkafka_tpu.ops.flash import _repeat_kv

                k, v = _repeat_kv(q, k, v)
                return mha(q, k, v, causal=True)
            return flash_attention(q, k, v, True)
        return mha(q, k, v, causal=True)

    def _moe_mlp(
        self, h: jax.Array, layer: Mapping[str, jax.Array]
    ) -> tuple[jax.Array, jax.Array]:
        if self.cfg.moe_dispatch == "capacity":
            return _moe_mlp_capacity(h, layer, self.cfg)
        return _moe_mlp(h, layer, self.cfg)

    @staticmethod
    def _seq_positions(local_len: int) -> jax.Array:
        """Global RoPE positions. Inside a manual region over 'sp' (a
        pipeline stage) the layer sees only its sequence shard, so offset by
        the shard index; in the auto-sharded path jit sees the global view."""
        from torchkafka_tpu.ops._compat import axis_is_manual

        if axis_is_manual("sp"):
            return lax.axis_index("sp") * local_len + jnp.arange(local_len)
        return jnp.arange(local_len)

    def _layer(
        self, x: jax.Array, layer: Mapping[str, jax.Array]
    ) -> tuple[jax.Array, jax.Array]:
        """One decoder layer. Returns (activation, router stats [2, E] for
        MoE configs / [2, 1] zeros otherwise — see ``router_aux``)."""
        cfg = self.cfg
        positions = self._seq_positions(x.shape[1])
        h = _rms_norm(x, layer["ln1"])
        q = jnp.einsum("bsd,dhe->bshe", h, load_weight(layer["wq"], cfg.dtype))
        k = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wk"], cfg.dtype))
        v = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wv"], cfg.dtype))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if cfg.n_kv_heads != cfg.n_heads and not (
            self._use_flash or self._use_ulysses
        ):
            # GQA: dense/ring paths need explicit head repeat; the flash
            # kernels (and ulysses, which calls them per head-shard) serve
            # K < H through their kv index map instead of materialising
            # H/K× the kv bytes in HBM.
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        attn = self._attention(q, k, v)
        x = x + jnp.einsum("bshe,hed->bsd", attn, load_weight(layer["wo"], cfg.dtype))
        h = _rms_norm(x, layer["ln2"])
        if cfg.is_moe:
            mlp_out, stats = self._moe_mlp(h, layer)
            return x + mlp_out, stats
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_gate"], cfg.dtype)))
        up = jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_up"], cfg.dtype))
        x = x + jnp.einsum("bsf,fd->bsd", gate * up, load_weight(layer["w_down"], cfg.dtype))
        return x, jnp.zeros((2, 1), jnp.float32)

    def trunk(
        self, params: dict, tokens: jax.Array
    ) -> tuple[jax.Array, jax.Array]:
        """tokens [B, S] int32 → (final-norm hidden states [B, S, D] in
        compute dtype, mean per-layer router aux loss). Everything except
        the lm_head projection — split out so ``loss`` can feed the fused
        blocked CE without ever materialising [B, S, V] logits."""
        cfg = self.cfg
        x = embed_rows(params["embed"], tokens, cfg.dtype)
        n_tokens = tokens.shape[0] * tokens.shape[1]

        if self.mesh is not None and self.mesh.shape.get("pp", 1) > 1:
            # GPipe over the stacked layers; embed/head/norm stay outside the
            # pipeline (replicated across pp). Router stats accumulate
            # through the schedule (valid-tick masked) and psum across
            # pp (and sp when manual) into full-batch sums — the aux here
            # equals the pp=1 value up to summation order.
            # With sp>1 the stage also binds 'sp' manually so ring attention
            # runs its collectives directly inside the stage body.
            from jax.sharding import PartitionSpec as _P

            from torchkafka_tpu.ops.pipeline import gpipe

            sp_size = self.mesh.shape.get("sp", 1)
            if sp_size > 1 and not (self._use_ring or self._use_ulysses):
                raise ValueError(
                    "a pp mesh with sp>1 requires sequence-parallel "
                    "attention (attn_impl='ring', 'ulysses', or 'auto')"
                )
            layer_fn = lambda a, layer: self._layer(a, layer)  # noqa: E731
            if cfg.remat:
                layer_fn = jax.checkpoint(layer_fn)
            x, stats = gpipe(
                layer_fn, params["layers"], x,
                mesh=self.mesh, axis="pp", microbatches=cfg.pp_microbatches,
                extra_manual={"sp"} if sp_size > 1 else set(),
                act_spec=_P(None, "sp", None) if sp_size > 1 else None,
                collect_stats=True,
            )
        else:
            def body(x, layer):
                x, stats = self._layer(x, layer)
                return x, stats

            if cfg.remat:
                body = jax.checkpoint(body)
            unroll = cfg.scan_unroll
            if unroll is None:
                # Auto-unroll only when no mesh axis shards the WEIGHTS.
                # The ~15% unroll win (PERF.md) was measured single-chip;
                # under tp/fsdp the unrolled backward's per-layer grad
                # intermediates make SPMD fall back to replicate-then-
                # repartition ("[SPMD] Involuntary full rematerialization"
                # — reproduced on a data2×fsdp2×tp2 mesh, gone at
                # unroll=1), which costs far more than the unroll saves.
                weight_sharded = self.mesh is not None and any(
                    self.mesh.shape.get(ax, 1) > 1
                    for ax in ("tp", "fsdp", "ep")
                )
                unroll = (
                    cfg.n_layers if cfg.n_layers <= 8 and not weight_sharded
                    else 1
                )
            x, stats = lax.scan(body, x, params["layers"], unroll=unroll)
        # stats: [L, 2, E] token-summed routing statistics; per-layer aux,
        # averaged over layers (identical math in both branches).
        aux = jnp.mean(jax.vmap(lambda s: router_aux(s, n_tokens))(stats))
        return _rms_norm(x, params["ln_f"]), aux

    def __call__(
        self, params: dict, tokens: jax.Array, *, return_aux: bool = False
    ):
        """tokens [B, S] int32 → logits [B, S, V] float32 (and, with
        ``return_aux``, the mean per-layer router load-balance loss)."""
        x, aux = self.trunk(params, tokens)
        logits = jnp.einsum(
            "bsd,dv->bsv", x, load_weight(params["lm_head"], self.cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        if return_aux:
            return logits, aux
        return logits

    def _use_fused_ce(self, params: dict) -> bool:
        """Fused blocked CE engages unless disabled, sequence-sharded (the
        block scan would serialise over sp), or the head is quantized."""
        if self.cfg.ce_block_size == 0:
            return False
        if self.mesh is not None and self.mesh.shape.get("sp", 1) > 1:
            return False
        return not isinstance(params["lm_head"], QTensor)

    def loss(
        self, params: dict, tokens: jax.Array, mask: jax.Array | None = None
    ) -> jax.Array:
        """Next-token cross-entropy. mask [B, S] 1=real row/token, 0=padding
        (the ingest batcher's valid_mask — padded rows must not train).

        The forward runs at full length S (so the sequence stays divisible
        by the sp axis) and the shift happens on the loss side: position i
        predicts token i+1, the final position is masked out. The default
        path is the fused blocked CE (ops/xent.py) — full [B, S, V] logits
        are never materialised; sp>1 / quantized heads take the dense path.
        """
        cfg = self.cfg
        x, aux = self.trunk(params, tokens)
        aux = aux if (cfg.is_moe and cfg.router_aux_coef > 0) else 0.0
        # Shift once for both CE paths: position i predicts token i+1; the
        # final position (and padded rows) carry mask 0. Keeping full length
        # S also keeps the batch divisible over an sp axis.
        targets = jnp.pad(tokens[:, 1:], ((0, 0), (0, 1)))
        m = jnp.ones(tokens.shape, jnp.float32) if mask is None else mask
        m = jnp.pad(m[:, 1:].astype(jnp.float32), ((0, 0), (0, 1)))
        if self._use_fused_ce(params):
            ce = fused_softmax_xent(
                x, params["lm_head"], targets, m,
                cfg.ce_block_size, cfg.dtype,
            )
        else:
            # Dense fallback shares the oracle implementation (ops/xent.py)
            # — one CE definition, two materialisation strategies.
            ce = dense_softmax_xent(
                x, load_weight(params["lm_head"], cfg.dtype), targets, m,
                cfg.dtype,
            )
        return ce + cfg.router_aux_coef * aux


# ----------------------------------------------------------------- train step


def batch_spec(mesh: Mesh) -> P:
    """Tokens [B, S]: batch over data(+fsdp), sequence over sp."""
    daxes = tuple(a for a in ("data", "fsdp") if a in mesh.shape)
    return P(daxes if daxes else None, "sp" if "sp" in mesh.shape else None)


def opt_shardings_like(opt_state, params, p_shardings, repl):
    """Sharding tree for an optax state: a leaf that MIRRORS a param
    (its tree path ends with the param's full path and the shapes match
    — adam's mu/nu, sgd's trace, any chain wrapping them) takes that
    param's sharding; everything else (step counts, scalars) replicates.

    Exists for jax 0.4.x, where a with_sharding_constraint on params
    inside a jitted init commits the PARAMS' output layout but
    ``optimizer.init``'s mirrors still come back replicated — which then
    breaks the train step's donation aliasing (input sharding !=
    out_shardings, an XLA INTERNAL error). On newer jax the constraint
    propagates and committing to the same layout is a no-op."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    p_leaves, _ = tree_flatten_with_path(params)
    s_leaves, _ = tree_flatten_with_path(p_shardings)
    by_path = {
        tuple(str(k) for k in path): (leaf.shape, sh)
        for (path, leaf), (_, sh) in zip(p_leaves, s_leaves)
    }

    def pick(path, leaf):
        key = tuple(str(k) for k in path)
        for i in range(len(key)):
            hit = by_path.get(key[i:])
            if hit is not None and hit[0] == getattr(leaf, "shape", None):
                return hit[1]
        return repl

    o_leaves, treedef = tree_flatten_with_path(opt_state)
    return tree_unflatten(treedef, [pick(p, l) for p, l in o_leaves])


def make_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    optimizer: Any,
) -> tuple[Callable[[jax.Array], tuple], Callable[..., tuple]]:
    """Build (init_fn, step_fn) jitted over the mesh.

    init_fn(rng) → (params, opt_state) laid out per ``param_specs``.
    step_fn(params, opt_state, tokens, mask) → (params, opt_state, loss);
    donates params/opt_state, so the caller rebinds them every step.
    """
    model = Transformer(cfg, mesh)
    p_shardings = shardings_for_mesh(mesh, param_specs(cfg))
    tok_sharding = NamedSharding(mesh, batch_spec(mesh))
    mask_sharding = tok_sharding
    repl = NamedSharding(mesh, P())

    @jax.jit
    def _init(rng):
        params = init_params(rng, cfg)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        opt_state = optimizer.init(params)
        return params, opt_state

    # Pin the optimizer state's layout EXPLICITLY on both sides of the
    # donated step (see opt_shardings_like): jax 0.4.x neither propagates
    # the param constraint into optimizer.init's output nor infers the
    # step's opt output layout consistently with its input — either
    # mismatch is an XLA INTERNAL donation-aliasing error. eval_shape
    # gives the opt tree without materialising it.
    p_shapes, o_shapes = jax.eval_shape(_init, jax.random.key(0))
    o_shardings = opt_shardings_like(o_shapes, p_shapes, p_shardings, repl)

    def init_fn(rng: jax.Array):
        params, opt_state = _init(rng)
        opt_state = jax.device_put(opt_state, o_shardings)
        return params, opt_state

    def _step(params, opt_state, tokens, mask):
        # Constrain inside the jit (rather than via in_shardings) so callers
        # may pass batches committed to any layout — e.g. the ingest path's
        # data-axis-only sharding — and XLA inserts the reshard to add sp.
        tokens = jax.lax.with_sharding_constraint(tokens, tok_sharding)
        mask = jax.lax.with_sharding_constraint(mask, mask_sharding)
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, mask)
        # Pin grads to the param layout at the AD boundary. Without this,
        # SPMD is free to pick a layout for the backward's grad-accumulation
        # intermediates from the (batch-sharded) contraction operands, then
        # discovers at the optimizer that the param layout differs and falls
        # back to replicate-then-repartition ("[SPMD] Involuntary full
        # rematerialization" on fsdp×tp meshes) — wasted HBM and ICI every
        # step. Constraining here lets the wanted layout propagate back
        # into the transpose instead.
        grads = jax.lax.with_sharding_constraint(grads, p_shardings)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        return params, opt_state, loss

    step_fn = jax.jit(
        _step,
        donate_argnums=(0, 1),
        out_shardings=(p_shardings, o_shardings, repl),
    )
    return init_fn, step_fn


def count_params(params: dict) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))

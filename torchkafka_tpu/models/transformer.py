"""Llama-style decoder-only transformer, TPU-first.

Net-new vs the reference (no model code in its tree — SURVEY.md §2); this is
the flagship consumer of the ingest pipeline for BASELINE configs 3 and 5.

Design choices, all for the TPU/XLA compilation model:

- **Pure pytree params, stacked layers.** Parameters are a plain dict with
  every per-layer tensor stacked on a leading [L, ...] axis, and the forward
  pass runs ``lax.scan`` over that axis: one traced layer body, compile time
  independent of depth, and a single PartitionSpec per tensor covers all
  layers.
- **bfloat16 compute, float32 params/accumulators.** Matmuls hit the MXU in
  bf16 (``cfg.dtype``); master weights, optimizer moments, softmax and the
  online-attention recurrence stay f32.
- **Sharding by spec, collectives by XLA.** ``param_specs`` gives each tensor
  a PartitionSpec over a {data, fsdp, tp, sp} mesh (2D "megatron" TP for
  attention/MLP, fsdp sharding on the other matmul dim, replicated norms).
  The train step is one ``jax.jit`` whose in/out shardings are those specs —
  XLA inserts all_gather/reduce_scatter/psum where the math demands them.
  No hand-written collectives outside ring attention's explicit ppermute.
- **Sequence parallelism is real.** With an ``sp`` axis of size > 1 the
  activations are sharded over sequence, and attention runs as ring
  attention (torchkafka_tpu.ops.attention) so no device ever materialises
  the full sequence. RoPE/norms/MLP are elementwise-in-sequence and need no
  communication.
- **Remat.** ``cfg.remat`` wraps the scanned layer body in
  ``jax.checkpoint``, trading recompute for HBM — the standard long-context
  lever.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchkafka_tpu.ops.attention import mha, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8  # < n_heads → grouped-query attention
    d_ff: int = 1376
    max_seq_len: int = 512
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16  # compute dtype (MXU)
    param_dtype: Any = jnp.float32  # master weights
    remat: bool = False
    # 'dense' | 'flash' | 'ring' | 'auto': auto picks ring when the mesh has
    # sp>1, else the Pallas flash kernel on TPU, else dense XLA.
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self) -> None:
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must divide by n_kv_heads")


# --------------------------------------------------------------------- params


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs per tensor, over mesh axes {data, fsdp, tp, sp}.

    Megatron 2D layout: the "output features" dim of up-projections (wq/wk/wv,
    w_gate/w_up) and the vocab dim shard over ``tp``; the opposing dim shards
    over ``fsdp`` (ZeRO-3-style weight sharding that XLA turns into
    all_gathers just-in-time). Mesh axes absent from the actual Mesh are
    stripped by ``shardings_for_mesh``.
    """
    return {
        "embed": P("tp", "fsdp"),  # [V, D]
        "layers": {
            "ln1": P(None, None),  # [L, D]
            "ln2": P(None, None),
            "wq": P(None, "fsdp", "tp", None),  # [L, D, H, Dh]
            "wk": P(None, "fsdp", "tp", None),  # [L, D, K, Dh]
            "wv": P(None, "fsdp", "tp", None),
            "wo": P(None, "tp", None, "fsdp"),  # [L, H, Dh, D]
            "w_gate": P(None, "fsdp", "tp"),  # [L, D, F]
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),  # [L, F, D]
        },
        "ln_f": P(None),  # [D]
        "lm_head": P("fsdp", "tp"),  # [D, V]
    }


def shardings_for_mesh(mesh: Mesh, specs: Any) -> Any:
    """Convert specs → NamedShardings, dropping axis names the mesh lacks."""

    def fix(spec: P) -> NamedSharding:
        parts = []
        for entry in spec:
            if entry is None:
                parts.append(None)
            elif isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in mesh.shape)
                parts.append(kept if kept else None)
            else:
                parts.append(entry if entry in mesh.shape else None)
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map(
        fix, specs, is_leaf=lambda x: isinstance(x, P)
    )


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Scaled-normal init, stacked [L, ...] per layer tensor."""
    keys = jax.random.split(rng, 8)
    dm, dff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    h, k, dh, v = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.vocab_size
    pd = cfg.param_dtype

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) / math.sqrt(fan_in)).astype(pd)

    return {
        "embed": norm(keys[0], (v, dm), dm),
        "layers": {
            "ln1": jnp.ones((nl, dm), pd),
            "ln2": jnp.ones((nl, dm), pd),
            "wq": norm(keys[1], (nl, dm, h, dh), dm),
            "wk": norm(keys[2], (nl, dm, k, dh), dm),
            "wv": norm(keys[3], (nl, dm, k, dh), dm),
            "wo": norm(keys[4], (nl, h, dh, dm), h * dh),
            "w_gate": norm(keys[5], (nl, dm, dff), dm),
            "w_up": norm(keys[6], (nl, dm, dff), dm),
            "w_down": norm(keys[7], (nl, dff, dm), dff),
        },
        "ln_f": jnp.ones((dm,), pd),
        "lm_head": norm(keys[0], (dm, v), dm),
    }


# -------------------------------------------------------------------- forward


def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (xf * rms).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B, S, H, D]; positions: [S] global positions."""
    dim = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Transformer:
    """Functional model bound to a config (and optionally a mesh for SP)."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh | None = None):
        self.cfg = cfg
        self.mesh = mesh
        use_ring = (
            cfg.attn_impl == "ring"
            or (
                cfg.attn_impl == "auto"
                and mesh is not None
                and mesh.shape.get("sp", 1) > 1
            )
        )
        self._use_ring = use_ring and mesh is not None
        self._use_flash = not self._use_ring and (
            cfg.attn_impl == "flash"
            or (cfg.attn_impl == "auto" and jax.default_backend() == "tpu")
        )

    def init(self, rng: jax.Array) -> dict:
        return init_params(rng, self.cfg)

    def _attention(self, q, k, v):
        if self._use_ring:
            return ring_attention(q, k, v, mesh=self.mesh, axis_name="sp", causal=True)
        if self._use_flash:
            from torchkafka_tpu.ops.flash import flash_attention

            return flash_attention(q, k, v, True)
        return mha(q, k, v, causal=True)

    def _layer(self, x: jax.Array, layer: Mapping[str, jax.Array]) -> jax.Array:
        cfg = self.cfg
        positions = jnp.arange(x.shape[1])
        h = _rms_norm(x, layer["ln1"])
        q = jnp.einsum("bsd,dhe->bshe", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dke->bske", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dke->bske", h, layer["wv"].astype(cfg.dtype))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if cfg.n_kv_heads != cfg.n_heads:  # GQA: repeat kv heads
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        attn = self._attention(q, k, v)
        x = x + jnp.einsum("bshe,hed->bsd", attn, layer["wo"].astype(cfg.dtype))
        h = _rms_norm(x, layer["ln2"])
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cfg.dtype)))
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
        x = x + jnp.einsum("bsf,fd->bsd", gate * up, layer["w_down"].astype(cfg.dtype))
        return x

    def __call__(self, params: dict, tokens: jax.Array) -> jax.Array:
        """tokens [B, S] int32 → logits [B, S, V] float32."""
        cfg = self.cfg
        x = params["embed"].astype(cfg.dtype)[tokens]

        def body(x, layer):
            return self._layer(x, layer), None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, params["layers"])
        x = _rms_norm(x, params["ln_f"])
        return jnp.einsum(
            "bsd,dv->bsv", x, params["lm_head"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )

    def loss(
        self, params: dict, tokens: jax.Array, mask: jax.Array | None = None
    ) -> jax.Array:
        """Next-token cross-entropy. mask [B, S] 1=real row/token, 0=padding
        (the ingest batcher's valid_mask — padded rows must not train).

        The forward runs at full length S (so the sequence stays divisible by
        the sp axis) and the shift happens on the logits.
        """
        logits = self(params, tokens)[:, :-1]
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        if mask is None:
            return nll.mean()
        m = mask[:, 1:].astype(nll.dtype)
        return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)


# ----------------------------------------------------------------- train step


def batch_spec(mesh: Mesh) -> P:
    """Tokens [B, S]: batch over data(+fsdp), sequence over sp."""
    daxes = tuple(a for a in ("data", "fsdp") if a in mesh.shape)
    return P(daxes if daxes else None, "sp" if "sp" in mesh.shape else None)


def make_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    optimizer: Any,
) -> tuple[Callable[[jax.Array], tuple], Callable[..., tuple]]:
    """Build (init_fn, step_fn) jitted over the mesh.

    init_fn(rng) → (params, opt_state) laid out per ``param_specs``.
    step_fn(params, opt_state, tokens, mask) → (params, opt_state, loss);
    donates params/opt_state, so the caller rebinds them every step.
    """
    model = Transformer(cfg, mesh)
    p_shardings = shardings_for_mesh(mesh, param_specs(cfg))
    tok_sharding = NamedSharding(mesh, batch_spec(mesh))
    mask_sharding = tok_sharding
    repl = NamedSharding(mesh, P())

    @jax.jit
    def _init(rng):
        params = init_params(rng, cfg)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        opt_state = optimizer.init(params)
        return params, opt_state

    def init_fn(rng: jax.Array):
        return _init(rng)

    def _step(params, opt_state, tokens, mask):
        # Constrain inside the jit (rather than via in_shardings) so callers
        # may pass batches committed to any layout — e.g. the ingest path's
        # data-axis-only sharding — and XLA inserts the reshard to add sp.
        tokens = jax.lax.with_sharding_constraint(tokens, tok_sharding)
        mask = jax.lax.with_sharding_constraint(mask, mask_sharding)
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        return params, opt_state, loss

    step_fn = jax.jit(
        _step,
        donate_argnums=(0, 1),
        out_shardings=(p_shardings, None, repl),
    )
    return init_fn, step_fn


def count_params(params: dict) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))

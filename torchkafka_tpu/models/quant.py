"""Weight-only int8 quantization for inference and serving.

Net-new vs the reference (no model code in its tree, SURVEY.md §2), and
TPU-motivated: autoregressive decode is weight-bandwidth-bound (every step
streams the full parameter set from HBM for a few rows of activations), so
int8 weights halve the bytes vs bf16 — and quarter them vs f32 masters —
for ~2× the decode roofline. Activations stay in ``cfg.dtype``; weights are
dequantized per-use INSIDE the layer scan, so only one layer's bf16 weights
ever exist at a time and the HBM residency win is preserved.

Scheme: symmetric absmax, per-OUTPUT-channel (the scale reduces over each
weight's contraction axes), int8 in [-127, 127]:

    scale = absmax(w, contraction_axes) / 127
    q     = round(w / scale)              w ≈ q · scale

``QTensor`` is a pytree (NamedTuple), so quantized params flow through
jit/donation/device_put like any other param tree. The model reads weights
through ``load_weight``/``embed_rows``, which accept either a plain array
or a QTensor — training code paths are untouched (quantization is a
post-training transform; there is no QAT here).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    q: jax.Array  # int8, the original weight's shape
    scale: jax.Array  # f32, 1-sized on the contraction axes (broadcasts)


def quantize(w: jax.Array, contract_axes: tuple[int, ...]) -> QTensor:
    """Symmetric absmax int8 over ``contract_axes`` (the dims a matmul
    reduces over), leaving one scale per output channel."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=contract_axes, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def load_weight(w, dtype):
    """Array or QTensor → compute-dtype array (dequant at the use site).
    The q·scale product runs in f32 (int8 promotes) and casts ONCE — casting
    scale to bf16 first would round it to 8 mantissa bits before the
    multiply, stacking avoidable error on top of the int8 error."""
    if isinstance(w, QTensor):
        return (w.q * w.scale).astype(dtype)
    return w.astype(dtype)


def embed_rows(w, tokens, dtype):
    """Embedding lookup for array or QTensor tables: gather int8 rows FIRST,
    then scale — never dequantizes the whole table."""
    if isinstance(w, QTensor):
        return (w.q[tokens] * w.scale[tokens]).astype(dtype)
    return w[tokens].astype(dtype)


def quant_kv_groups(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Group-wise symmetric absmax int8 over the LAST (head_dim) axis:
    [..., Dh] → (int8 [..., Dh], f32 scale [...]) — one scale per
    (position, head) group, the KV-cache analog of ``quantize``'s
    per-output-channel weight scheme. Shared by the dense int8 slot
    pool (serve._slot_layer_step_q) and the int8 PAGED pool (the block
    pools quantize each written position through the same groups, so
    int8-paged serving is token-exact vs int8-dense serving — the
    groups, not just the scheme, are identical)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


# Contraction axes per weight name (stacked [L, ...] layout); embeddings are
# per-row (the gather output dim).
_LAYER_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,),  # [L, D, H, Dh] contract D
    "wo": (1, 2),  # [L, H, Dh, D] contract (H, Dh)
    "w_gate": (1,), "w_up": (1,),  # [L, D, F]
    "w_down": (1,),  # [L, F, D]
}
_MOE_AXES = {
    "w_gate": (2,), "w_up": (2,),  # [L, E, D, F] contract D
    "w_down": (2,),  # [L, E, F, D] contract F
}


def quantize_params(params: dict, cfg) -> dict:
    """Post-training int8 of every matmul/embedding weight; norms and the
    MoE router (tiny, routing-sensitive) stay in their original dtype."""
    layer_axes = dict(_LAYER_AXES)
    if cfg.is_moe:
        layer_axes.update(_MOE_AXES)
    layers = {}
    for name, w in params["layers"].items():
        if name in ("ln1", "ln2", "router"):
            layers[name] = w
        else:
            layers[name] = quantize(w, layer_axes[name])
    return {
        "embed": quantize(params["embed"], (1,)),  # [V, D] per-row
        "layers": layers,
        "ln_f": params["ln_f"],
        "lm_head": quantize(params["lm_head"], (0,)),  # [D, V] per-column
    }


def quantize_specs(specs: dict, cfg) -> dict:
    """PartitionSpec tree matching ``quantize_params``'s output structure:
    each quantized leaf becomes QTensor(q=<original spec>, scale=<spec with
    the contraction axes unsharded>) — a size-1 scale dim cannot shard.
    Feed the result to ``shardings_for_mesh`` to serve quantized params on
    a tp/fsdp mesh."""
    from jax.sharding import PartitionSpec as P

    layer_axes = dict(_LAYER_AXES)
    if cfg.is_moe:
        layer_axes.update(_MOE_AXES)

    def scale_spec(spec: P, contract_axes: tuple[int, ...]) -> P:
        parts = list(spec)
        for ax in contract_axes:
            parts[ax] = None
        return P(*parts)

    def q_spec(spec: P, contract_axes: tuple[int, ...]) -> QTensor:
        return QTensor(q=spec, scale=scale_spec(spec, contract_axes))

    layers = {}
    for name, spec in specs["layers"].items():
        if name in ("ln1", "ln2", "router"):
            layers[name] = spec
        else:
            # Layer specs carry a leading pp axis over L (param_specs'
            # with_pp), so the contraction axes line up with the weights.
            layers[name] = q_spec(spec, layer_axes[name])
    return {
        "embed": q_spec(specs["embed"], (1,)),
        "layers": layers,
        "ln_f": specs["ln_f"],
        "lm_head": q_spec(specs["lm_head"], (0,)),
    }


def quantized_nbytes(tree) -> int:
    return sum(
        x.nbytes for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "nbytes")
    )

"""KV-cache autoregressive decoding for the flagship transformer.

BASELINE config 5's consumer: prompts stream in from a topic, the model
generates continuations, and the prompts' offsets commit only after
generation completes (commit-after-step, extended to a multi-step op).

TPU/XLA shape discipline: the caches are preallocated to a static
``max_len = prompt_len + max_new`` and written with
``lax.dynamic_update_slice``; the decode loop is a ``lax.scan`` over
``max_new`` steps (trace once, no per-step recompilation); attention masks by
position against the static cache. Greedy (temperature=0) or categorical
sampling.

The prefill math intentionally reuses the exact layer code of
``Transformer.__call__`` (one implementation, no drift); only the
single-token decode step is specialised here.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from torchkafka_tpu.models.quant import embed_rows, load_weight
from torchkafka_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    _moe_mlp,
    _rms_norm,
    _rope,
)


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, max_len, K, Dh]
    v: jax.Array  # [L, B, max_len, K, Dh]


def _attend_cached(x, q, cache_k, cache_v, valid, layer, cfg):
    """Shared decode tail: grouped-query attention over the kv cache,
    masked softmax, output projection and the MLP residual. x: [B, 1, D];
    q: [B, 1, H, Dh]; caches [B, M, K, Dh]; valid: [B, M] or [M] bool mask
    of readable cache positions. Single source of truth for both the
    lockstep decode (scalar position, generate.py) and the continuous-
    batching server's per-slot decode (serve.py).

    GQA runs as a grouped einsum — q reshaped [B, S, K, rep, Dh] contracts
    directly against the [B, M, K, Dh] cache. Decode is cache-bandwidth
    bound, so never materialising a repeated H-head cache copy is the
    difference between reading K heads and reading H heads per token."""
    b, s, h, dh = q.shape
    kk = cache_k.astype(cfg.dtype)
    vv = cache_v.astype(cfg.dtype)
    n_kv = kk.shape[2]
    rep = h // n_kv
    qg = q.reshape(b, s, n_kv, rep, dh)
    scores = jnp.einsum(
        "bskre,bmke->bkrsm", qg, kk, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(cfg.head_dim))
    if valid.ndim == 1:
        valid = valid[None, :]
    scores = jnp.where(valid[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum(
        "bkrsm,bmke->bskre", probs.astype(cfg.dtype), vv,
        preferred_element_type=jnp.float32,
    ).astype(cfg.dtype).reshape(b, s, h, dh)
    x = x + jnp.einsum("bshe,hed->bsd", attn, load_weight(layer["wo"], cfg.dtype))
    h = _rms_norm(x, layer["ln2"])
    if cfg.is_moe:
        # Decode always routes EXACTLY (dense dispatch) regardless of
        # cfg.moe_dispatch: capacity drops are a training
        # throughput/regularization tradeoff; at inference every token
        # gets its routed experts (standard MoE serving semantics — see
        # the moe_dispatch config comment).
        mlp_out, _stats = _moe_mlp(h, layer, cfg)
        return x + mlp_out
    gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_gate"], cfg.dtype)))
    up = jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_up"], cfg.dtype))
    return x + jnp.einsum("bsf,fd->bsd", gate * up, load_weight(layer["w_down"], cfg.dtype))


def _project_qkv(x, layer, cfg):
    """RMSNorm + q/k/v projections for one decode token. x: [B, 1, D]."""
    h = _rms_norm(x, layer["ln1"])
    q = jnp.einsum("bsd,dhe->bshe", h, load_weight(layer["wq"], cfg.dtype))
    k = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wk"], cfg.dtype))
    v = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wv"], cfg.dtype))
    return q, k, v


def _layer_step(x, layer, cache_k, cache_v, pos, cfg):
    """One token through one layer. x: [B, 1, D]; caches [B, max_len, K, Dh];
    pos: scalar current position. Returns (x, new_cache_k, new_cache_v)."""
    q, k, v = _project_qkv(x, layer, cfg)
    positions = pos[None] if pos.ndim == 0 else pos
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    cache_k = lax.dynamic_update_slice(cache_k, k.astype(cache_k.dtype), (0, pos, 0, 0))
    cache_v = lax.dynamic_update_slice(cache_v, v.astype(cache_v.dtype), (0, pos, 0, 0))
    valid = jnp.arange(cache_k.shape[1]) <= pos  # attend to cache[0..pos]
    x = _attend_cached(x, q, cache_k, cache_v, valid, layer, cfg)
    return x, cache_k, cache_v


def prefill(params, cfg: TransformerConfig, tokens: jax.Array, max_len: int):
    """Full forward over the prompt, capturing k/v into static caches.

    tokens: [B, S] → (last-position logits [B, V], KVCache with [0,S) filled).
    Uses Transformer.__call__ for the logits (single source of truth) and an
    auxiliary scan to capture per-layer k/v.
    """
    # Inference is mesh-less here: a training config that requested a
    # sequence-parallel attn_impl ('ring'/'ulysses') must still be servable
    # from its checkpoint, so fall back to the adaptive spelling rather than
    # tripping the constructor's misconfigured-mesh guard.
    if cfg.attn_impl in ("ring", "ulysses"):
        model = Transformer(dataclasses.replace(cfg, attn_impl="auto"))
    else:
        model = Transformer(cfg)
    batch, seq = tokens.shape
    x = embed_rows(params["embed"], tokens, cfg.dtype)
    positions = jnp.arange(seq)

    def capture(x, layer):
        # Same math as Transformer._layer, but returns k/v for the cache.
        h = _rms_norm(x, layer["ln1"])
        k = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wk"], cfg.dtype))
        v = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wv"], cfg.dtype))
        k = _rope(k, positions, cfg.rope_theta)
        x, _stats = model._layer(x, layer)
        return x, (k, v)

    x, (ks, vs) = lax.scan(capture, x, params["layers"])
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum(
        "bd,dv->bv", x[:, -1], load_weight(params["lm_head"], cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    cache_k = jnp.zeros((nl, batch, max_len, kh, dh), cfg.dtype)
    cache_v = jnp.zeros((nl, batch, max_len, kh, dh), cfg.dtype)
    cache_k = lax.dynamic_update_slice(cache_k, ks.astype(cfg.dtype), (0, 0, 0, 0, 0))
    cache_v = lax.dynamic_update_slice(cache_v, vs.astype(cfg.dtype), (0, 0, 0, 0, 0))
    return logits, KVCache(cache_k, cache_v)


def _decode_one(params, cfg, cache: KVCache, token: jax.Array, pos: jax.Array):
    """token: [B] → logits [B, V], updated cache. pos: scalar position."""
    x = embed_rows(params["embed"], token, cfg.dtype)[:, None, :]  # [B,1,D]

    def body(x, inputs):
        layer, ck, cv = inputs
        x, ck, cv = _layer_step(x, layer, ck, cv, pos, cfg)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum(
        "bd,dv->bv", x[:, 0], load_weight(params["lm_head"], cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, KVCache(ck, cv)


def generate(
    params,
    cfg: TransformerConfig,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
):
    """prompt: [B, S] int32 → generated [B, max_new] int32 (greedy when
    temperature == 0). Jit-friendly: static prompt length and max_new."""
    batch, seq = prompt.shape
    max_len = seq + max_new
    logits, cache = prefill(params, cfg, prompt, max_len)
    if rng is None:
        rng = jax.random.key(0)

    def pick(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    first = pick(logits, rng)

    def step(carry, i):
        token, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = _decode_one(params, cfg, cache, token, seq + i)
        nxt = pick(logits, sub)
        return (nxt, cache, key), token

    (_, _, _), tokens = lax.scan(
        step, (first, cache, rng), jnp.arange(max_new)
    )
    return jnp.transpose(tokens, (1, 0))  # [B, max_new]

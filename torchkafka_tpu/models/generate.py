"""KV-cache autoregressive decoding for the flagship transformer.

BASELINE config 5's consumer: prompts stream in from a topic, the model
generates continuations, and the prompts' offsets commit only after
generation completes (commit-after-step, extended to a multi-step op).

TPU/XLA shape discipline: the caches are preallocated to a static
``max_len = prompt_len + max_new`` and written with
``lax.dynamic_update_slice``; the decode loop is a ``lax.scan`` over
``max_new`` steps (trace once, no per-step recompilation); attention masks by
position against the static cache. Greedy (temperature=0) or categorical
sampling.

The prefill math intentionally reuses the exact layer code of
``Transformer.__call__`` (one implementation, no drift); only the
single-token decode step is specialised here.

Model-sharded decode: pass ``mesh`` (and commit params to
``serving_shardings``) to run tp/fsdp/data-sharded inference — kv heads
shard over tp, the batch over data, and weights keep their training
layouts, so anything too big for one chip (bf16 8B+, long KV budgets)
serves across a slice. BASELINE config 5 names Llama-3-8B on v5e-8; the
multichip dryrun (``__graft_entry__.dryrun_multichip``) proves this path
end-to-end on a virtual mesh.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchkafka_tpu.models.quant import QTensor, embed_rows, load_weight, quantize_specs
from torchkafka_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    _moe_mlp,
    _rms_norm,
    _rope,
    param_specs,
    shardings_for_mesh,
)


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, max_len, K, Dh]
    v: jax.Array  # [L, B, max_len, K, Dh]


# --------------------------------------------------------------- sampling


def filter_logits(
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Temperature → top-k → top-p filtering over [..., V] logits, the
    standard composition order; masked-out entries go to -inf so a
    categorical draw never selects them. STATIC shapes throughout — top-k
    is a ``lax.top_k`` threshold compare, top-p a full sort + exclusive
    cumulative-probability mask — so the serving tick stays one compiled
    program for any (k, p). Ties at either threshold are kept (>= the
    boundary value), the rule the NumPy reference in tests/test_sampling.py
    mirrors bit-for-bit at f32."""
    logits = logits.astype(jnp.float32) / jnp.float32(temperature)
    neg = jnp.float32(-jnp.inf)
    if top_k is not None and 0 < top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        srt = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep a token while the cumulative probability BEFORE it is still
        # < p: the minimal prefix whose mass reaches p, never empty.
        keep = (cum - probs) < jnp.float32(top_p)
        n_keep = jnp.sum(keep.astype(jnp.int32), axis=-1, keepdims=True)
        kth = jnp.take_along_axis(srt, n_keep - 1, axis=-1)
        logits = jnp.where(logits < kth, neg, logits)
    return logits


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """[..., V] logits → [...] int32 token ids. ``temperature == 0`` is
    greedy argmax (top_k/top_p ignored — the filter cannot change the
    argmax); otherwise a categorical draw over ``filter_logits``. One
    sampling definition serves the lockstep ``generate`` and the
    continuous-batching server, so their sampled paths cannot drift."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = filter_logits(
        logits, temperature=temperature, top_k=top_k, top_p=top_p
    )
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


def check_sampling_params(top_k: int | None, top_p: float | None) -> None:
    """Shared eager validation: a bad knob should fail at construction, not
    as an XLA shape error three dispatches later."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


# ------------------------------------------------------------ mesh-sharded
# Model-sharded decode (BASELINE config 5 names an 8-chip v5e slice): the
# same tp/fsdp layouts training uses (param_specs) carry into inference,
# the KV cache shards its kv-head axis over tp (each shard attends over its
# own heads' cache — attention is head-local until wo's psum), and the
# batch/slot axis shards over data. XLA inserts the megatron collectives
# (psum after wo and w_down, logit all-gather) from the layouts alone —
# no hand-written collectives, same design rule as the train step.


def check_serving_mesh(cfg: TransformerConfig, mesh: Mesh, *, batch: int | None = None) -> None:
    """Divisibility guards for model-sharded decode, covering every dim the
    ``serving_shardings`` layouts split: device_put requires EVEN shards,
    so each sharded dim must divide its axis or the placement fails deep in
    JAX internals instead of here. tp shards heads (wq's H, the cache's K),
    the vocab (embed rows / lm_head columns) and d_ff (w_gate/w_down); fsdp
    shards d_model; ep shards experts; data shards the batch/slot axis."""
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and (cfg.n_heads % tp or cfg.n_kv_heads % tp):
        raise ValueError(
            f"tp={tp} must divide n_heads={cfg.n_heads} and "
            f"n_kv_heads={cfg.n_kv_heads} for sharded decode"
        )
    if tp > 1 and (cfg.vocab_size % tp or cfg.d_ff % tp):
        raise ValueError(
            f"tp={tp} must divide vocab_size={cfg.vocab_size} and "
            f"d_ff={cfg.d_ff} (embed/lm_head/MLP shard those dims over tp)"
        )
    fsdp = mesh.shape.get("fsdp", 1)
    if fsdp > 1 and cfg.d_model % fsdp:
        raise ValueError(
            f"fsdp={fsdp} must divide d_model={cfg.d_model} "
            "(weight fan-in dims shard over fsdp)"
        )
    ep = mesh.shape.get("ep", 1)
    if ep > 1 and cfg.is_moe and cfg.n_experts % ep:
        raise ValueError(
            f"ep={ep} must divide n_experts={cfg.n_experts}"
        )
    pp = mesh.shape.get("pp", 1)
    if pp > 1 and cfg.n_layers % pp:
        raise ValueError(
            f"pp={pp} must divide n_layers={cfg.n_layers} (layer-stacked "
            "weights shard over pp; decode is layer-sharded storage, not a "
            "pipelined schedule)"
        )
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError(
            "serving meshes must not carry an sp axis: decode is one token "
            "per step (nothing to sequence-shard) and prefill under sp "
            "would engage ring attention against an unsharded prompt — "
            "shard kv heads over tp and slots over data instead"
        )
    dp = mesh.shape.get("data", 1)
    if batch is not None and dp > 1 and batch % dp:
        raise ValueError(
            f"batch/slots={batch} must divide by the data axis ({dp})"
        )


def serving_shardings(cfg: TransformerConfig, mesh: Mesh, params) -> dict:
    """NamedShardings for a serving param tree — plain (bf16/f32) or
    int8-quantized (QTensor leaves get quantize_specs' scale handling).
    The layouts are exactly the training ``param_specs``: a checkpoint
    trained tp/fsdp-sharded serves in place."""
    specs = param_specs(cfg)
    if isinstance(params["lm_head"], QTensor):
        specs = quantize_specs(specs, cfg)
    return shardings_for_mesh(mesh, specs)


def kv_sharding(mesh: Mesh) -> NamedSharding:
    """KVCache [L, B, M, K, Dh]: slots/batch over data, kv heads over tp."""
    return shardings_for_mesh(mesh, P(None, "data", None, "tp", None))


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """int8-KV scale tensors [L, B, M, K] (the payload layout minus the
    head_dim axis): slots over data, kv heads over tp."""
    return shardings_for_mesh(mesh, P(None, "data", None, "tp"))


def slot_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Per-slot vectors [B, ...] (tokens, positions, masks): over data."""
    return shardings_for_mesh(mesh, P("data", *([None] * (ndim - 1))))


def kv_kmajor_sharding(mesh: Mesh) -> NamedSharding:
    """K-MAJOR dense int8 pool [L, B, K, M, Dh] (the Pallas dynamic-
    length kernel's layout): slots over data, kv heads over tp — the
    same axes as ``kv_sharding``, transposed with the layout."""
    return shardings_for_mesh(mesh, P(None, "data", "tp", None, None))


def kv_kmajor_scale_sharding(mesh: Mesh) -> NamedSharding:
    """K-major int8 scale tensors [L, B, K, M]."""
    return shardings_for_mesh(mesh, P(None, "data", "tp", None))


def paged_pool_sharding(mesh: Mesh) -> NamedSharding:
    """Paged block pool [L, NB, bs, K, Dh]: kv heads over tp, blocks
    REPLICATED over data — blocks are shared storage (any slot's table
    may reference any block, and radix prefix blocks are read by slots
    on every data shard), so the slot axis that shards over data in the
    dense pool has no analog here; each data shard holds the full pool
    for its K/tp heads and XLA all-gathers the per-shard scatter
    updates to keep the replicas coherent."""
    return shardings_for_mesh(mesh, P(None, None, None, "tp", None))


def paged_pool_kmajor_sharding(mesh: Mesh) -> NamedSharding:
    """K-major-per-block int8 paged payloads [L, NB, K, bs, Dh]: kv
    heads over tp, blocks replicated over data (see
    ``paged_pool_sharding``)."""
    return shardings_for_mesh(mesh, P(None, None, "tp", None, None))


def paged_scale_kmajor_sharding(mesh: Mesh) -> NamedSharding:
    """K-major-per-block int8 paged scales [L, NB, K, bs]."""
    return shardings_for_mesh(mesh, P(None, None, "tp", None))


def _constrain_cache(cache: KVCache, mesh: Mesh | None) -> KVCache:
    if mesh is None:
        return cache
    s = kv_sharding(mesh)
    return KVCache(
        lax.with_sharding_constraint(cache.k, s),
        lax.with_sharding_constraint(cache.v, s),
    )


def _attend_cached(
    x, q, cache_k, cache_v, valid, layer, cfg,
    k_scale=None, v_scale=None,
):
    """Shared decode tail: grouped-query attention over the kv cache,
    masked softmax, output projection and the MLP residual. x: [B, S, D];
    q: [B, S, H, Dh]; caches [B, M, K, Dh]; valid: [M], [B, M], or
    [B, S, M] (per-query masks — the multi-query verify step of
    speculative decoding) bool mask of readable cache positions. Single
    source of truth for the lockstep decode (scalar position,
    generate.py), the continuous-batching server's per-slot decode
    (serve.py), and spec decode's verify (spec_decode.py), in BOTH
    cache dtypes.

    GQA runs as a grouped einsum — q reshaped [B, S, K, rep, Dh] contracts
    directly against the [B, M, K, Dh] cache. Decode is cache-bandwidth
    bound, so never materialising a repeated H-head cache copy is the
    difference between reading K heads and reading H heads per token.

    ``k_scale``/``v_scale`` ([B, M, K] f32): int8-KV mode — the caches
    hold int8 payloads and the per-position scales are FOLDED onto the
    small score/prob tensors (exact: scales are constant along the Dh
    contraction), so the big operands carry only an int8→compute cast:

        scores[..., m] = (q · k_int8[m]) · k_scale[m]
        out            = (probs · v_scale) @ v_int8

    Measured caveat (PERF.md): on v5e XLA still materialises the
    converted operand as a buffer rather than fusing the cast into the
    dot's HBM read, so int8 KV trades ~20% equal-slot throughput for
    ~2× pool capacity; a Pallas decode kernel streaming int8 directly
    is the known fix."""
    b, s, h, dh = q.shape
    kk = cache_k.astype(cfg.dtype)
    vv = cache_v.astype(cfg.dtype)
    n_kv = kk.shape[2]
    rep = h // n_kv
    qg = q.reshape(b, s, n_kv, rep, dh)
    scores = jnp.einsum(
        "bskre,bmke->bkrsm", qg, kk, preferred_element_type=jnp.float32
    )
    if k_scale is not None:
        # [B, M, K] → [B, K, 1, 1, M] over [B, K, rep, S, M] scores.
        scores = scores * k_scale.transpose(0, 2, 1)[:, :, None, None, :]
    scores = scores / jnp.sqrt(jnp.float32(cfg.head_dim))
    if valid.ndim == 1:
        valid = valid[None, :]
    if valid.ndim == 2:  # [B, M]: one mask for every query position
        vmask = valid[:, None, None, None, :]
    else:  # [B, S, M]: per-query masks (multi-query verify, spec decode)
        vmask = valid[:, None, None, :, :]
    scores = jnp.where(vmask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        probs = probs * v_scale.transpose(0, 2, 1)[:, :, None, None, :]
    attn = jnp.einsum(
        "bkrsm,bmke->bskre", probs.astype(cfg.dtype), vv,
        preferred_element_type=jnp.float32,
    ).astype(cfg.dtype).reshape(b, s, h, dh)
    return _attn_tail(x, attn, layer, cfg)


def _attn_tail(x, attn, layer, cfg):
    """Post-attention residual: output projection + the MLP block. Shared
    by the bf16 cache read (``_attend_cached``) and the int8-KV read
    (serve._attend_cached_q8), so the layer math has one definition."""
    x = x + jnp.einsum("bshe,hed->bsd", attn, load_weight(layer["wo"], cfg.dtype))
    h = _rms_norm(x, layer["ln2"])
    if cfg.is_moe:
        # Decode always routes EXACTLY (dense dispatch) regardless of
        # cfg.moe_dispatch: capacity drops are a training
        # throughput/regularization tradeoff; at inference every token
        # gets its routed experts (standard MoE serving semantics — see
        # the moe_dispatch config comment).
        mlp_out, _stats = _moe_mlp(h, layer, cfg)
        return x + mlp_out
    gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_gate"], cfg.dtype)))
    up = jnp.einsum("bsd,df->bsf", h, load_weight(layer["w_up"], cfg.dtype))
    return x + jnp.einsum("bsf,fd->bsd", gate * up, load_weight(layer["w_down"], cfg.dtype))


def _project_qkv(x, layer, cfg):
    """RMSNorm + q/k/v projections for decode queries. x: [B, S, D] —
    S=1 for a decode tick, S=k+1 for spec decode's multi-query verify."""
    h = _rms_norm(x, layer["ln1"])
    q = jnp.einsum("bsd,dhe->bshe", h, load_weight(layer["wq"], cfg.dtype))
    k = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wk"], cfg.dtype))
    v = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wv"], cfg.dtype))
    return q, k, v


def _layer_step(x, layer, cache_k, cache_v, pos, cfg):
    """One token through one layer. x: [B, 1, D]; caches [B, max_len, K, Dh];
    pos: scalar current position. Returns (x, new_cache_k, new_cache_v)."""
    q, k, v = _project_qkv(x, layer, cfg)
    positions = pos[None] if pos.ndim == 0 else pos
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    cache_k = lax.dynamic_update_slice(cache_k, k.astype(cache_k.dtype), (0, pos, 0, 0))
    cache_v = lax.dynamic_update_slice(cache_v, v.astype(cache_v.dtype), (0, pos, 0, 0))
    valid = jnp.arange(cache_k.shape[1]) <= pos  # attend to cache[0..pos]
    x = _attend_cached(x, q, cache_k, cache_v, valid, layer, cfg)
    return x, cache_k, cache_v


def prefill(
    params, cfg: TransformerConfig, tokens: jax.Array, max_len: int,
    mesh: Mesh | None = None,
):
    """Full forward over the prompt, capturing k/v into static caches.

    tokens: [B, S] → (last-position logits [B, V], KVCache with [0,S) filled).
    Uses Transformer.__call__ for the logits (single source of truth) and an
    auxiliary scan to capture per-layer k/v.

    With ``mesh``, the prompt batch is constrained over data and the cache
    over (data, tp) — weights are assumed committed to ``serving_shardings``
    layouts. Prefill attention under a mesh dispatches through the model's
    own rules: on TPU the Pallas flash kernels run under shard_map
    (``flash_attention_sharded`` — a Pallas call is opaque to GSPMD, but
    batch/head-parallel attention needs no collectives), falling back to
    the dense XLA body off-TPU or when the batch/heads don't split evenly.
    """
    # A training config that requested a sequence-parallel attn_impl
    # ('ring'/'ulysses') must still be servable from its checkpoint, so
    # fall back to the adaptive spelling rather than tripping the
    # constructor's misconfigured-mesh guard. An explicit 'dense' or
    # 'flash' passes through unchanged — a deliberate kernel opt-out (or
    # opt-in) is the user's call, mesh or not.
    if cfg.attn_impl in ("ring", "ulysses"):
        model = Transformer(dataclasses.replace(cfg, attn_impl="auto"), mesh)
    else:
        model = Transformer(cfg, mesh)
    if mesh is not None:
        tokens = lax.with_sharding_constraint(
            tokens, slot_sharding(mesh, tokens.ndim)
        )
    batch, seq = tokens.shape
    x = embed_rows(params["embed"], tokens, cfg.dtype)
    positions = jnp.arange(seq)

    def capture(x, layer):
        # Same math as Transformer._layer, but returns k/v for the cache.
        h = _rms_norm(x, layer["ln1"])
        k = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wk"], cfg.dtype))
        v = jnp.einsum("bsd,dke->bske", h, load_weight(layer["wv"], cfg.dtype))
        k = _rope(k, positions, cfg.rope_theta)
        x, _stats = model._layer(x, layer)
        return x, (k, v)

    x, (ks, vs) = lax.scan(capture, x, params["layers"])
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum(
        "bd,dv->bv", x[:, -1], load_weight(params["lm_head"], cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    cache_k = jnp.zeros((nl, batch, max_len, kh, dh), cfg.dtype)
    cache_v = jnp.zeros((nl, batch, max_len, kh, dh), cfg.dtype)
    cache_k = lax.dynamic_update_slice(cache_k, ks.astype(cfg.dtype), (0, 0, 0, 0, 0))
    cache_v = lax.dynamic_update_slice(cache_v, vs.astype(cfg.dtype), (0, 0, 0, 0, 0))
    return logits, _constrain_cache(KVCache(cache_k, cache_v), mesh)


def _decode_one(
    params, cfg, cache: KVCache, token: jax.Array, pos: jax.Array,
    mesh: Mesh | None = None,
):
    """token: [B] → logits [B, V], updated cache. pos: scalar position."""
    x = embed_rows(params["embed"], token, cfg.dtype)[:, None, :]  # [B,1,D]

    def body(x, inputs):
        layer, ck, cv = inputs
        x, ck, cv = _layer_step(x, layer, ck, cv, pos, cfg)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum(
        "bd,dv->bv", x[:, 0], load_weight(params["lm_head"], cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, _constrain_cache(KVCache(ck, cv), mesh)


def generate(
    params,
    cfg: TransformerConfig,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    mesh: Mesh | None = None,
):
    """prompt: [B, S] int32 → generated [B, max_new] int32 (greedy when
    temperature == 0). Jit-friendly: static prompt length and max_new.

    ``top_k``/``top_p``: nucleus/top-k filtering applied per step when
    sampling (``sample_logits``) — static-shape, same definition the
    serving path uses, differential-tested against a NumPy reference.

    ``mesh``: model-sharded decode — params must be committed to
    ``serving_shardings`` layouts (kv heads shard over tp, batch over
    data); token-exact vs the mesh-less path (differential-tested)."""
    check_sampling_params(top_k, top_p)
    batch, seq = prompt.shape
    if mesh is not None:
        check_serving_mesh(cfg, mesh, batch=batch)
        params = lax.with_sharding_constraint(
            params, serving_shardings(cfg, mesh, params)
        )
    max_len = seq + max_new
    logits, cache = prefill(params, cfg, prompt, max_len, mesh)
    if rng is None:
        rng = jax.random.key(0)

    def pick(logits, key):
        return sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )

    first = pick(logits, rng)

    def step(carry, i):
        token, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = _decode_one(params, cfg, cache, token, seq + i, mesh)
        nxt = pick(logits, sub)
        return (nxt, cache, key), token

    (_, _, _), tokens = lax.scan(
        step, (first, cache, rng), jnp.arange(max_new)
    )
    return jnp.transpose(tokens, (1, 0))  # [B, max_new]

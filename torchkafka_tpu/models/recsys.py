"""DLRM-style streaming recommender: embedding tables + interaction + MLPs.

The canonical consumer of a Kafka ingest pipeline is not an LLM but a
click-through-rate model fed by an event stream — the workload the
reference's users run DataLoader ingest for (the reference itself ships no
model code, SURVEY.md §2). This module makes that concrete, TPU-first:

- **Embedding tables are the capacity.** Production CTR models put >90% of
  parameters in the tables, so they shard ROW-wise over the mesh's ``tp``
  axis (``P("tp", None)``): each device holds a vocab stripe, and
  ``jnp.take`` over the sharded table lowers to XLA's distributed gather
  over ICI — no parameter server, no host-side sharding logic (the DLRM
  pattern re-expressed as sharding annotations instead of NCCL alltoall).
- **MLPs are MXU food.** Bottom (dense features) and top (post-interaction)
  towers run in bf16; they are small relative to the tables and replicate.
- **Feature interaction** is the standard pairwise-dot block: stack the
  bottom output with the per-feature embeddings [B, C+1, E] and take the
  upper triangle of the Gram matrix — one batched matmul, no gathers.

Record layout for the streaming path (``parse_record`` /
``make_processor``): float32 label, float32[dense_dim] dense features,
int32[n_tables] categorical ids — the shape a Kafka CTR event naturally
has after feature hashing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchkafka_tpu.models.quant import embed_rows, load_weight, quantize
from torchkafka_tpu.models.transformer import shardings_for_mesh
from torchkafka_tpu.source.records import Record


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    dense_dim: int = 13
    vocab_sizes: tuple[int, ...] = tuple([100_000] * 8)
    embed_dim: int = 64
    bottom_mlp: tuple[int, ...] = (128, 64)  # last entry must equal embed_dim
    top_mlp: tuple[int, ...] = (256, 128, 1)  # last entry must be 1 (logit)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def __post_init__(self):
        if self.bottom_mlp[-1] != self.embed_dim:
            raise ValueError(
                f"bottom_mlp must end at embed_dim ({self.embed_dim}) so the "
                f"dense vector joins the interaction block; got {self.bottom_mlp}"
            )
        if self.top_mlp[-1] != 1:
            raise ValueError("top_mlp must end at 1 (the CTR logit)")

    @property
    def n_tables(self) -> int:
        return len(self.vocab_sizes)

    @property
    def n_interactions(self) -> int:
        n = self.n_tables + 1  # +1: the bottom-MLP dense vector
        return n * (n - 1) // 2


def param_specs(cfg: DLRMConfig) -> dict:
    """Tables shard rows over ``tp`` (the capacity axis); towers replicate
    (they are KBs next to the tables' GBs). Axes absent from the actual
    mesh are stripped by ``shardings_for_mesh``."""
    return {
        "tables": {f"t{i}": P("tp", None) for i in range(cfg.n_tables)},
        "bottom": [(P(None, None), P(None)) for _ in cfg.bottom_mlp],
        "top": [(P(None, None), P(None)) for _ in cfg.top_mlp],
    }


def init_params(rng: jax.Array, cfg: DLRMConfig) -> dict:
    n_bottom, n_top = len(cfg.bottom_mlp), len(cfg.top_mlp)
    keys = jax.random.split(rng, cfg.n_tables + n_bottom + n_top)
    pd = cfg.param_dtype

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) / np.sqrt(fan_in)).astype(pd)

    def mlp(keys, dims, d_in):
        layers = []
        for key, d_out in zip(keys, dims):
            wkey, bkey = jax.random.split(key)
            layers.append((norm(wkey, (d_in, d_out), d_in), jnp.zeros(d_out, pd)))
            d_in = d_out
        return layers

    tables = {
        f"t{i}": norm(keys[i], (v, cfg.embed_dim), cfg.embed_dim)
        for i, v in enumerate(cfg.vocab_sizes)
    }
    return {
        "tables": tables,
        "bottom": mlp(keys[cfg.n_tables:cfg.n_tables + n_bottom], cfg.bottom_mlp, cfg.dense_dim),
        "top": mlp(
            keys[cfg.n_tables + n_bottom:],
            cfg.top_mlp,
            cfg.n_interactions + cfg.embed_dim,
        ),
    }


def _tower(x: jax.Array, layers, dtype, final_linear: bool) -> jax.Array:
    for i, (w, b) in enumerate(layers):
        x = x @ load_weight(w, dtype) + b.astype(dtype)
        if not (final_linear and i == len(layers) - 1):
            x = jax.nn.relu(x)
    return x


def forward(params: dict, dense: jax.Array, cats: jax.Array, cfg: DLRMConfig) -> jax.Array:
    """dense [B, dense_dim] f32, cats [B, n_tables] int32 → logits [B] f32.

    Weights may be plain arrays or int8 ``QTensor``s
    (``quantize_dlrm_params``): table lookups gather int8 rows FIRST and
    scale only the gathered rows — the 4× table-memory win decode-side
    recommenders quantize for."""
    dt = cfg.dtype
    bottom = _tower(dense.astype(dt), params["bottom"], dt, final_linear=False)
    embs = [
        embed_rows(params["tables"][f"t{i}"], cats[:, i], dt)
        for i in range(cfg.n_tables)
    ]
    feats = jnp.stack([bottom, *embs], axis=1)  # [B, C+1, E]
    gram = jnp.einsum(
        "bie,bje->bij", feats, feats, preferred_element_type=jnp.float32
    )
    iu, ju = jnp.triu_indices(feats.shape[1], k=1)
    inter = gram[:, iu, ju].astype(dt)  # [B, n_interactions]
    top_in = jnp.concatenate([bottom, inter], axis=-1)
    logits = _tower(top_in, params["top"], dt, final_linear=True)
    return logits[:, 0].astype(jnp.float32)


def loss_fn(
    params: dict,
    dense: jax.Array,
    cats: jax.Array,
    labels: jax.Array,
    mask: jax.Array,
    cfg: DLRMConfig,
) -> jax.Array:
    """Masked mean sigmoid binary cross-entropy (mask: padded batcher rows
    contribute nothing — the reference's None-drop analog at batch level)."""
    logits = forward(params, dense, cats, cfg)
    per_row = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
    mask = mask.astype(jnp.float32)
    return (per_row * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def _dlrm_batch_spec(mesh: Mesh) -> P:
    daxes = tuple(a for a in ("data", "fsdp") if a in mesh.shape)
    return P(daxes if daxes else None)


def make_dlrm_train_step(
    cfg: DLRMConfig,
    mesh: Mesh,
    optimizer: Any,
) -> tuple[Callable[[jax.Array], tuple], Callable[..., tuple]]:
    """(init_fn, step_fn) jitted over the mesh, same contract as the
    transformer's ``make_train_step``: step_fn(params, opt_state, dense,
    cats, labels, mask) → (params, opt_state, loss), donating state."""
    p_shardings = shardings_for_mesh(mesh, param_specs(cfg))
    row = NamedSharding(mesh, _dlrm_batch_spec(mesh))
    mat = NamedSharding(mesh, P(*_dlrm_batch_spec(mesh), None))
    repl = NamedSharding(mesh, P())

    @jax.jit
    def _init(rng):
        params = init_params(rng, cfg)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        return params, optimizer.init(params)

    # Pin the optimizer mirrors' layout on BOTH sides of the donated step
    # (jax 0.4.x: optimizer.init returns them replicated despite the param
    # constraint, and inferred step outputs need not match the input —
    # either way donation aliasing dies; see transformer.opt_shardings_like).
    from torchkafka_tpu.models.transformer import opt_shardings_like

    p_shapes, o_shapes = jax.eval_shape(_init, jax.random.key(0))
    o_shardings = opt_shardings_like(o_shapes, p_shapes, p_shardings, repl)

    def init_fn(rng):
        params, opt_state = _init(rng)
        return params, jax.device_put(opt_state, o_shardings)

    def _step(params, opt_state, dense, cats, labels, mask):
        dense = jax.lax.with_sharding_constraint(dense, mat)
        cats = jax.lax.with_sharding_constraint(cats, mat)
        labels = jax.lax.with_sharding_constraint(labels, row)
        mask = jax.lax.with_sharding_constraint(mask, row)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, dense, cats, labels, mask, cfg
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        params = jax.lax.with_sharding_constraint(params, p_shardings)
        return params, opt_state, loss

    step_fn = jax.jit(
        _step, donate_argnums=(0, 1),
        out_shardings=(p_shardings, o_shardings, repl),
    )
    return init_fn, step_fn


# ------------------------------------------------------------- stream glue


def record_nbytes(cfg: DLRMConfig) -> int:
    return 4 * (1 + cfg.dense_dim + cfg.n_tables)


def parse_record(value: bytes, cfg: DLRMConfig) -> dict[str, np.ndarray]:
    """float32 label | float32[dense_dim] | int32[n_tables] → element pytree."""
    d = cfg.dense_dim
    head = np.frombuffer(value, np.float32, count=1 + d)
    cats = np.frombuffer(value, np.int32, count=cfg.n_tables, offset=4 * (1 + d))
    return {"label": head[0], "dense": head[1 : 1 + d], "cats": cats}


def make_processor(cfg: DLRMConfig) -> Callable[[Record], dict | None]:
    """Per-record processor for ``KafkaStream`` (None-drop on short records,
    the reference's ``_process`` contract). See ``make_chunk_processor``
    for the throughput path."""
    nbytes = record_nbytes(cfg)

    def processor(record: Record) -> dict | None:
        if len(record.value) != nbytes:
            return None
        return parse_record(record.value, cfg)

    return processor


def make_chunk_processor(cfg: DLRMConfig):
    """Chunked CTR-record decoder: one native ``gather_rows`` call per poll
    chunk into a [K, nbytes] byte matrix, then three columnar views — no
    per-record Python objects. Identical semantics to ``make_processor``
    (wrong-length records drop), ~10-30x its throughput; differential-
    tested in tests/test_recsys.py."""
    from torchkafka_tpu import native
    from torchkafka_tpu.transform.processor import chunked

    nbytes = record_nbytes(cfg)
    d = cfg.dense_dim

    @chunked
    def process(records: list[Record]):
        values = [r.value for r in records]
        keep = np.fromiter(
            (len(v) == nbytes for v in values), np.bool_, count=len(values)
        )
        if not keep.any():
            return None, keep
        if not keep.all():
            values = [v for v in values if len(v) == nbytes]
        rows = native.gather_rows(values, nbytes, np.uint8)
        head = np.ascontiguousarray(rows[:, : 4 * (1 + d)]).view(np.float32)
        cats = np.ascontiguousarray(rows[:, 4 * (1 + d):]).view(np.int32)
        out = {
            "label": np.ascontiguousarray(head[:, 0]),
            "dense": np.ascontiguousarray(head[:, 1 : 1 + d]),
            "cats": cats,
        }
        return out, (None if keep.all() else keep)

    return process


def quantize_dlrm_params(params: dict) -> dict:
    """Post-training int8 of the capacity-heavy weights: tables per-ROW
    (the gather output dim — scale applies to gathered rows only) and
    tower matmul weights per-output-column; biases stay full precision
    (tiny, additive). The result flows through ``forward``/``loss_fn``
    unchanged — inference only, like ``models.quant.quantize_params``."""
    return {
        "tables": {
            name: quantize(w, (1,)) for name, w in params["tables"].items()
        },
        "bottom": [(quantize(w, (0,)), b) for w, b in params["bottom"]],
        "top": [(quantize(w, (0,)), b) for w, b in params["top"]],
    }


def count_params(params: dict) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))

"""Model zoo: the on-chip consumers of ingested batches.

The reference ships zero model code (SURVEY.md §2) — its batches flow into
whatever the user's training loop does. Our BASELINE scenarios (configs 4-5,
BASELINE.md) make the consumers concrete: a vision CNN for image-topic
inference and a Llama-style decoder for prompt-topic generation/training.
These models exist so the framework's end-to-end contract — ingest → global
sharded batch → pjit step → barrier → commit — is demonstrated and benched
against real MXU-shaped compute, not a stub.
"""

# NOTE: the `generate` FUNCTION is deliberately NOT re-exported here —
# binding it at package level would shadow the `models.generate` SUBMODULE
# attribute (import torchkafka_tpu.models.generate would yield the
# function), breaking module-style access to prefill/serving helpers.
from torchkafka_tpu.models.generate import check_serving_mesh, serving_shardings
from torchkafka_tpu.models.recsys import DLRMConfig, make_dlrm_train_step
from torchkafka_tpu.models.spec_decode import (
    SpecStats,
    speculative_generate,
    truncated_draft,
)
from torchkafka_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    make_train_step,
)

__all__ = [
    "DLRMConfig",
    "SpecStats",
    "Transformer",
    "TransformerConfig",
    "check_serving_mesh",
    "make_dlrm_train_step",
    "make_train_step",
    "serving_shardings",
    "speculative_generate",
    "truncated_draft",
]

"""Named model scales for the flagship decoder: 45M → 1.3B → 8B-class.

BASELINE.md's serving configs name Llama-3-8B on v5e; the framework's own
models must therefore be instantiable — and benchmarkable — at the scales
where serving actually pressures HBM, not only the 45M stand-in
(VERDICT r3 item 1). The shapes follow the Llama family conventions
(GQA with 8 kv heads, SwiGLU with d_ff ≈ 2.75·d_model, RoPE):

| scale | params | layout                              | serving dtype |
|-------|--------|-------------------------------------|---------------|
| 45m   | ~45M   | 512 × 4L, 8 heads (package default) | bf16          |
| 1b    | ~1.26B | 2048 × 24L, 16 q / 8 kv heads       | bf16 (2.5 GB) |
| 8b    | ~8.0B  | 4096 × 32L, 32 q / 8 kv heads,      | int8 (8.0 GB) |
|       |        | d_ff 14336, vocab 128256 (Llama-3)  |               |

``random_serving_params`` exists because 8B f32 masters are 32 GB — they
cannot be initialised then quantized on a 16 GB chip. For BENCHMARK weights
the distribution does not matter, only the bytes and shapes: int8 weights
are drawn uniform in [-127, 127] with per-output-channel scales chosen so
the dequantized magnitude matches the scaled-normal init (std = 1/√fan_in),
so matmul shapes, HBM traffic, and logit magnitudes are all serving-real
while peak init memory stays at the int8 footprint.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from torchkafka_tpu.models.quant import QTensor, quantized_nbytes
from torchkafka_tpu.models.transformer import TransformerConfig, init_params

# Total HBM bytes of a param tree (QTensor leaves count q + scale) — the
# serving-byte accounting name; one implementation (models/quant.py).
params_nbytes = quantized_nbytes

# Uniform over [-127, 127] has std 127/√3; scale = 1/(that · √fan_in) gives
# dequantized std 1/√fan_in, the init the trained path uses.
_UNIFORM_INT8_STD = 127.0 / math.sqrt(3.0)


def zoo_config(scale: str, *, max_seq_len: int = 512) -> TransformerConfig:
    """A named model scale. 45m/1b serve in bf16; 8b is built for the int8
    weight-only path (pair with ``random_serving_params(quantized=True)``
    or ``quantize_params``)."""
    if scale == "45m":
        # bf16 params like the larger scales: the zoo exists for SERVING
        # benchmarks, and f32 masters here made roofline accounting count
        # twice the bytes the chip actually streams (XLA hoists the
        # f32→bf16 cast out of the decode loop — VERDICT r4 weak #5).
        return TransformerConfig(
            max_seq_len=max_seq_len, param_dtype=jnp.bfloat16
        )
    if scale == "1b":
        return TransformerConfig(
            vocab_size=32_000, d_model=2048, n_layers=24, n_heads=16,
            n_kv_heads=8, d_ff=5632, max_seq_len=max_seq_len,
            dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
        )
    if scale == "8b":
        # Llama-3-8B's published shape (BASELINE.md config 5 names it).
        return TransformerConfig(
            vocab_size=128_256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq_len=max_seq_len,
            dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
        )
    raise ValueError(f"unknown scale {scale!r} (want 45m | 1b | 8b)")


def _rand_q(key: jax.Array, shape: tuple[int, ...],
            contract_axes: tuple[int, ...]) -> QTensor:
    """Benchmark-weight QTensor drawn directly in int8 (no f32 transient)."""
    q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
    fan_in = 1
    for ax in contract_axes:
        fan_in *= shape[ax]
    scale_shape = tuple(
        1 if ax in contract_axes else s for ax, s in enumerate(shape)
    )
    scale = jnp.full(
        scale_shape, 1.0 / (_UNIFORM_INT8_STD * math.sqrt(fan_in)), jnp.float32
    )
    return QTensor(q=q, scale=scale)


def random_serving_params(
    rng: jax.Array, cfg: TransformerConfig, *, quantized: bool
) -> dict:
    """Serving-shaped benchmark weights at the model's true byte footprint.

    quantized=False → the standard ``init_params`` (use a bf16
    ``param_dtype`` config so masters materialise at 2 bytes/param).
    quantized=True → int8 QTensors drawn directly (see module docstring):
    peak memory = the int8 footprint itself, which is what makes the
    8B-class servable on one 16 GB chip.
    """
    if not quantized:
        return jax.jit(lambda k: init_params(k, cfg))(rng)
    if cfg.is_moe:
        raise ValueError(
            "random_serving_params(quantized=True) covers the dense zoo "
            "scales; quantize a real MoE checkpoint via quantize_params"
        )
    from torchkafka_tpu.models.quant import _LAYER_AXES

    dm, dff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    h, k_, dh, v = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.vocab_size
    layer_axes = dict(_LAYER_AXES)
    shapes = {
        "wq": (nl, dm, h, dh), "wk": (nl, dm, k_, dh), "wv": (nl, dm, k_, dh),
        "wo": (nl, h, dh, dm),
        "w_gate": (nl, dm, dff), "w_up": (nl, dm, dff), "w_down": (nl, dff, dm),
    }

    # ONE jitted program for the whole tree: per-leaf jits cost a separate
    # compile each, and on remote-compile transports that is minutes of
    # wall clock for what is seconds of device work.
    def build(rng_key):
        keys = jax.random.split(rng_key, len(shapes) + 2)
        layers: dict = {
            "ln1": jnp.ones((nl, dm), jnp.float32),
            "ln2": jnp.ones((nl, dm), jnp.float32),
        }
        for key, (name, shape) in zip(keys[2:], shapes.items()):
            layers[name] = _rand_q(key, shape, layer_axes[name])
        return {
            "embed": _rand_q(keys[0], (v, dm), (1,)),
            "layers": layers,
            "ln_f": jnp.ones((dm,), jnp.float32),
            "lm_head": _rand_q(keys[1], (dm, v), (0,)),
        }

    return jax.jit(build)(rng)

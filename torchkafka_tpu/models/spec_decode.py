"""Speculative decoding: draft-model proposal, single-dispatch verify.

A small DRAFT model proposes ``k`` greedy tokens autoregressively (k tiny
decode dispatches on cheap weights); the TARGET model then scores all
k+1 positions in ONE multi-query decode step (one full weight stream for
up to k+1 tokens of progress) and accepts the longest prefix that
matches its own greedy choices, emitting one correction/bonus token from
its own logits. (The draft actually runs k+1 steps — the last only
ingests its k-th proposal so its cache stays contiguous across
full-accept rounds; see the in-body comment.) GREEDY ONLY, which buys
the strong contract: the emitted sequence is EXACTLY the target model's
greedy continuation for ANY draft sharing the vocab — the draft affects
only SPEED (via its acceptance rate), never content (differential-tested
in tests/test_spec.py).

Why this shape on TPU: decode is weight-streaming bound (PERF.md's
serving rooflines), so the unit of cost is "one full read of the target
weights". Plain decode buys 1 token per read; verify buys 1 + (accepted)
tokens per read for the same stream (the extra k query positions ride
the same weight tiles through the MXU), plus k+1 draft reads at
draft/target cost ratio. Expected speedup = E[accepted + 1] /
((k+1)·c + 1 + v) with c = draft/target tick ratio and v the multi-query
overhead — both measured in benchmarks/bench_spec.py rather than
assumed. Everything is static-shape: the per-round emission count is
dynamic but lives in POSITION BOOKKEEPING (per-row emitted counters and
a one-hot scatter into a padded buffer), not in array shapes, so the
whole loop jits as one ``lax.while_loop`` (guaranteed ≥1 token per
round, so it terminates in ≤ max_new rounds).

Cache discipline (the subtle part): both models' caches are written
SPECULATIVELY — verify writes k/v for all k+1 inputs, the draft for all
its k proposals — and rejected positions simply become STALE entries
beyond the per-row accepted watermark. Correctness holds because (a)
every attention masks by position against the watermark, so stale
entries are never read before (b) the next round's writes overwrite
them, write-before-attend, starting exactly at the watermark. Rollback
is therefore free: it IS the position bookkeeping. Caches are sized
S + max_new + 2k (overshoot margin: a round may start at position
S + emitted - 1 with emitted ≤ max_new + k after its own overshoot).

No reference analog (the reference ships no model code — SURVEY.md §2);
net-new TPU capability extending BASELINE config 5's generate consumer.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from torchkafka_tpu.models.generate import (
    KVCache,
    _attend_cached,
    _project_qkv,
    prefill,
)
from torchkafka_tpu.models.quant import embed_rows, load_weight
from torchkafka_tpu.models.transformer import (
    TransformerConfig,
    _rms_norm,
    _rope,
)


def truncated_draft(params, cfg: TransformerConfig, n_layers: int):
    """(draft_params, draft_cfg): the standard self-speculative cheap
    draft — the target's FIRST ``n_layers`` layers with its own
    embedding/final-norm/lm_head (all shared by reference, no copy).
    For a trained checkpoint this is the classic layer-skip draft
    (early layers carry most next-token signal); with random weights
    its acceptance is chance-level like any other draft — the
    exactness contract holds either way. Layer params are stacked
    [L, ...] leaves, so truncation is a leading-axis slice."""
    if not (1 <= n_layers <= cfg.n_layers):
        raise ValueError(
            f"n_layers must be in [1, {cfg.n_layers}], got {n_layers}"
        )
    draft_params = dict(params)
    draft_params["layers"] = jax.tree_util.tree_map(
        lambda x: x[:n_layers], params["layers"]
    )
    return draft_params, dataclasses.replace(cfg, n_layers=n_layers)


class SpecStats(NamedTuple):
    """Per-run counters (device arrays inside jit; ints after fetch)."""

    rounds: jax.Array     # verify dispatches executed
    accepted: jax.Array   # draft tokens accepted across all rows/rounds
    proposed: jax.Array   # draft tokens proposed across all rows/rounds


def _multi_step(params, cfg, cache: KVCache, tokens, pos_b):
    """S-query decode step at PER-ROW start positions: tokens [B, S]
    (token s sits at sequence position pos_b + s), writes k/v for all S
    inputs at [pos_b, pos_b + S), returns logits [B, S, V] (position
    pos_b + s + 1 predictions) and the updated cache. S=1 is exactly a
    per-row decode tick; S=k+1 is spec decode's verify. Queries mask
    causally per row (query s reads cache [0, pos_b + s]).

    Sibling implementations (update in step if the write/mask discipline
    changes): generate._layer_step (scalar-pos lockstep) and
    serve._slot_layer_step (per-row S=1, the measured serving tick —
    kept separate so spec-decode changes can never shift its published
    numbers)."""
    b, s = tokens.shape
    x = embed_rows(params["embed"], tokens, cfg.dtype)  # [B, S, D]
    positions = pos_b[:, None] + jnp.arange(s)[None, :]  # [B, S]

    rows = jnp.arange(b)[:, None]  # [B, 1] against positions [B, S]

    def body(x, inputs):
        layer, ck, cv = inputs
        q, k, v = _project_qkv(x, layer, cfg)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # Scatter writes (serve._slot_layer_step's r5 note: the vmapped
        # dynamic_update_slice lowering rewrites the whole pool per
        # layer; the scatter writes S rows per slot — measured +41%
        # tok/s on the 1B serving tick).
        ck = ck.at[rows, positions].set(k.astype(ck.dtype))
        cv = cv.at[rows, positions].set(v.astype(cv.dtype))
        valid = (
            jnp.arange(ck.shape[1])[None, None, :] <= positions[:, :, None]
        )  # [B, S, M] per-query causal masks
        x = _attend_cached(x, q, ck, cv, valid, layer, cfg)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum(
        "bsd,dv->bsv", x, load_weight(params["lm_head"], cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, KVCache(ck, cv)


def speculative_generate(
    target_params,
    target_cfg: TransformerConfig,
    draft_params,
    draft_cfg: TransformerConfig,
    prompt: jax.Array,
    max_new: int,
    *,
    k: int = 4,
):
    """prompt [B, S] int32 → (tokens [B, max_new] int32, SpecStats).

    ``tokens`` is EXACTLY ``generate(target_params, target_cfg, prompt,
    max_new)`` (greedy) up to f32 reduction order; the draft model only
    sets the speed. ``k``: draft tokens proposed per verify dispatch.
    Jit-friendly (static prompt length, max_new, k); quantized (QTensor)
    trees serve unchanged on either side.
    """
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft and target must share a vocab: "
            f"{draft_cfg.vocab_size} != {target_cfg.vocab_size}"
        )
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if max_new < 2:
        raise ValueError("max_new must be >= 2 (prefill emits token 0)")
    batch, seq = prompt.shape
    max_len = seq + max_new + 2 * k  # overshoot margin, see module docstring
    buf = max_new + k + 1

    t_logits0, t_cache = prefill(target_params, target_cfg, prompt, max_len)
    _d_logits0, d_cache = prefill(draft_params, draft_cfg, prompt, max_len)
    tok0 = jnp.argmax(t_logits0, axis=-1).astype(jnp.int32)  # [B]

    gen0 = jnp.zeros((batch, buf), jnp.int32)
    gen0 = gen0.at[:, 0].set(tok0)
    emitted0 = jnp.ones((batch,), jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    carry0 = (t_cache, d_cache, tok0, emitted0, gen0, zero, zero, zero)

    def cond(carry):
        _, _, _, emitted, _, _, _, _ = carry
        return jnp.any(emitted < max_new)

    def body(carry):
        t_cache, d_cache, last_tok, emitted, gen, rounds, acc, prop = carry
        act = emitted < max_new  # [B]
        base = seq + emitted - 1  # position of the last emitted token

        def dbody(c, j):
            d_cache, tok = c
            logits, d_cache = _multi_step(
                draft_params, draft_cfg, d_cache, tok[:, None], base + j
            )
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return (d_cache, nxt), nxt

        # k+1 draft steps for k proposals: the LAST step only INGESTS
        # d_k (its own output is discarded) so the draft cache stays
        # contiguous after a full-accept round — without it, position
        # base+k (= accepted d_k) would never receive draft k/v and the
        # next round's draft would attend over a stale hole (caught by
        # the perfect-draft test: acceptance collapsed to ~50%).
        (d_cache, _), d_toks = lax.scan(
            dbody, (d_cache, last_tok), jnp.arange(k + 1)
        )
        d = jnp.transpose(d_toks[:k])  # [B, k]

        v_in = jnp.concatenate([last_tok[:, None], d], axis=1)  # [B, k+1]
        t_logits, t_cache = _multi_step(
            target_params, target_cfg, t_cache, v_in, base
        )
        tga = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, k+1]

        match = tga[:, :k] == d
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        corr = jnp.take_along_axis(tga, n_acc[:, None], axis=1)[:, 0]  # [B]

        # Emit d[:, :n_acc] then the correction/bonus — a static loop of
        # one-hot row writes over the tiny [B, buf] buffer (measured at
        # parity with scatter on buffers this size — serve.py's gen
        # write; the POOL writes above use scatters, where it matters),
        # masked per row by j <= n_acc and activity.
        idx = jnp.arange(buf)[None, :]
        for j in range(k + 1):
            tok_j = d[:, j] if j < k else corr
            tok_j = jnp.where(j < n_acc, tok_j, corr)
            write = act & (j <= n_acc)
            sel = (idx == (emitted + j)[:, None]) & write[:, None]
            gen = jnp.where(sel, tok_j[:, None], gen)

        last_tok = jnp.where(act, corr, last_tok)
        n_act = jnp.sum(act.astype(jnp.int32))
        emitted = emitted + jnp.where(act, n_acc + 1, 0)
        rounds = rounds + (n_act > 0).astype(jnp.int32)
        acc = acc + jnp.sum(jnp.where(act, n_acc, 0))
        prop = prop + k * n_act
        return (t_cache, d_cache, last_tok, emitted, gen, rounds, acc, prop)

    (_, _, _, _, gen, rounds, acc, prop) = lax.while_loop(cond, body, carry0)
    return gen[:, :max_new], SpecStats(rounds, acc, prop)

"""Exception hierarchy for torchkafka_tpu.

The reference surfaces exactly one failure class to users:
``kafka.errors.CommitFailedError``, which it catches and logs as non-fatal
(/root/reference/src/kafka_dataset.py:131-135) because a failed commit simply
means the records will be re-delivered (at-least-once delivery). We keep that
contract but define our own transport-independent exceptions so the in-memory
broker, the kafka-python adapter, and any future native client all raise the
same types.
"""

from __future__ import annotations


class TpuKafkaError(Exception):
    """Base class for all torchkafka_tpu errors."""


class CommitFailedError(TpuKafkaError):
    """Offset commit was rejected (e.g. after a group rebalance).

    Mirrors kafka-python's ``CommitFailedError`` as used by the reference
    (/root/reference/src/kafka_dataset.py:22,131). Always survivable:
    uncommitted records are re-delivered to whichever consumer now owns the
    partitions, preserving at-least-once semantics.
    """


class ConsumerClosedError(TpuKafkaError):
    """Operation attempted on a closed consumer."""


class NotAssignedError(TpuKafkaError):
    """Commit/seek referenced a partition this consumer does not own."""


class ProducerClosedError(TpuKafkaError):
    """Operation attempted on a closed producer."""


class OutputDeliveryError(TpuKafkaError):
    """A produced output record terminally failed delivery (retries
    exhausted, too large, authorization). Raised instead of committing
    source offsets past the lost output: fail-stop = crash-before-commit,
    so the affected inputs re-deliver and the output regenerates."""


class UnknownTopicError(TpuKafkaError):
    """Topic does not exist on the broker."""


class BarrierError(TpuKafkaError):
    """The pod-wide commit barrier failed.

    The commit path fails *closed* on this: no offsets are committed, so Kafka
    re-delivers the batch — zero uncommitted-batch loss on host preemption.
    """

"""Exception hierarchy for torchkafka_tpu.

The reference surfaces exactly one failure class to users:
``kafka.errors.CommitFailedError``, which it catches and logs as non-fatal
(/root/reference/src/kafka_dataset.py:131-135) because a failed commit simply
means the records will be re-delivered (at-least-once delivery). We keep that
contract but define our own transport-independent exceptions so the in-memory
broker, the kafka-python adapter, and any future native client all raise the
same types.
"""

from __future__ import annotations


class TpuKafkaError(Exception):
    """Base class for all torchkafka_tpu errors.

    Every error carries a **retryable / terminal** classification via the
    ``retryable`` class attribute — the contract the resilience layer
    (``torchkafka_tpu/resilience``) keys its retry decisions on:

    - ``retryable = True``: a *transient transport fault* — the operation
      itself was sound and repeating it verbatim can succeed once the
      broker recovers (``BrokerUnavailableError``). Safe to retry because
      the affected operations are idempotent: polls re-fetch from the
      consumer position, commits carry absolute next-read offsets.
    - ``retryable = False`` (default): *terminal for that operation* —
      repeating the identical call cannot help. Either the protocol moved
      on (``CommitFailedError`` after a rebalance: the fix is
      re-delivery, not a retry of the stale-generation commit), the
      caller holds a bug (``NotAssignedError``, ``ConsumerClosedError``),
      or the failure is per-payload (``PoisonRecordError``: the record
      itself is bad and will fail identically forever — the escape hatch
      is the dead-letter quarantine, never a retry loop).

    Terminal is not the same as fatal: ``CommitFailedError`` is terminal
    *and survivable* (the watermark stays put and records re-deliver),
    while ``OutputDeliveryError`` is terminal and fail-stop (crash before
    commit).
    """

    retryable: bool = False


class CommitFailedError(TpuKafkaError):
    """Offset commit was rejected (e.g. after a group rebalance).

    Mirrors kafka-python's ``CommitFailedError`` as used by the reference
    (/root/reference/src/kafka_dataset.py:22,131). Always survivable:
    uncommitted records are re-delivered to whichever consumer now owns the
    partitions, preserving at-least-once semantics.
    """


class ConsumerClosedError(TpuKafkaError):
    """Operation attempted on a closed consumer."""


class NotAssignedError(TpuKafkaError):
    """Commit/seek referenced a partition this consumer does not own."""


class ProducerClosedError(TpuKafkaError):
    """Operation attempted on a closed producer."""


class OutputDeliveryError(TpuKafkaError):
    """A produced output record terminally failed delivery (retries
    exhausted, too large, authorization). Raised instead of committing
    source offsets past the lost output: fail-stop = crash-before-commit,
    so the affected inputs re-deliver and the output regenerates."""


class BrokerUnavailableError(TpuKafkaError):
    """The broker could not be reached (connection refused/reset, request
    timeout, leadership election in progress). RETRYABLE: polls and
    commits are idempotent, so repeating the operation after a backoff is
    always safe — ``ResilientConsumer`` does exactly that, behind a
    circuit breaker so a long outage degrades (empty polls, fast-failed
    commits) instead of hot-looping. ``ChaosConsumer`` raises this during
    injected outage windows."""

    retryable = True


class ProducerFencedError(TpuKafkaError):
    """This transactional producer's EPOCH is stale: another producer
    re-initialized the same ``transactional.id`` (``init_producer_id``
    bumps the epoch, Kafka's KIP-98 fencing), so every transactional
    operation this handle attempts is a zombie's. TERMINAL: the broker
    already aborted the old epoch's in-flight transaction when the new
    incarnation initialized — nothing produced under the stale epoch can
    ever reach the committed view, and retrying the identical call cannot
    help. The only valid responses are to re-initialize (becoming the
    newest incarnation and fencing the OTHER one) or to exit and let a
    supervisor respawn. The producer-side twin of ``FencedMemberError``:
    the lease protocol fences a consumer's commits, the epoch fences a
    producer's transactions, and the process fleet wires the two to the
    same replica identity."""


class StaleEpochError(TpuKafkaError):
    """A replicated WAL frame (or election probe) carried a LEADER epoch
    older than one this replica has already accepted: the sender is a
    DEPOSED leader — an election it never saw bumped the cell epoch — and
    its frame must be rejected, never applied. TERMINAL for the sender:
    the cell moved on, so retrying the identical append cannot help; the
    only valid responses are to step down (rejoin as a follower of the
    new epoch) or to exit. The cell-level twin of ``ProducerFencedError``:
    the producer epoch fences a zombie transaction, the cell epoch fences
    a zombie leader's entire replication stream."""


class QuorumLostError(BrokerUnavailableError):
    """The leader could not place a WAL frame on a MAJORITY of replicas
    (followers unreachable, or a majority stale-fenced this leader's
    epoch), so the mutation was never acknowledged. RETRYABLE — it
    subclasses ``BrokerUnavailableError`` because the client-side story
    is identical to a broker outage: the operation is idempotent, the
    cell is (re-)electing, and repeating the call after a backoff reaches
    whichever leader the new epoch crowned. Nothing un-acked ever
    surfaces in the committed view, so the retry can never double-apply."""


class TransactionStateError(TpuKafkaError):
    """A transactional operation was issued in the wrong state — produce
    or commit with no open transaction, begin-inside-begin with a
    different outcome pending, offsets on a producer that never
    initialized. TERMINAL (caller bug): Kafka's INVALID_TXN_STATE. The
    transaction protocol is a strict begin → produce*/offsets* →
    commit-or-abort cycle; anything else indicates the caller lost track
    of its own state machine."""


class FencedMemberError(TpuKafkaError):
    """This group member has been FENCED: its heartbeat lease expired (or
    a supervisor fenced it explicitly) and the broker evicted it from the
    group. TERMINAL for the member: the rebalance already bumped the
    generation and handed its partitions to survivors, so nothing it does
    with its old identity can be honored — commits fail generation-checked
    (``CommitFailedError``), heartbeats raise this. The only valid
    responses are to re-join as a fresh member or to exit and let a
    supervisor respawn. Kafka's UNKNOWN_MEMBER_ID, with the lease made
    explicit."""


class JournalLockedError(TpuKafkaError):
    """A decode journal file is exclusively owned by another LIVE process.
    Journal files are single-writer (one replica incarnation each);
    two live processes writing one file would interleave tmp-renames and
    corrupt the warm-failover state. A lock held by a dead process (or by
    this same process) is stale and silently stolen — SIGKILL leaves no
    chance to clean up."""


class PoisonRecordError(TpuKafkaError):
    """A record's *payload* cannot be processed (undecodable bytes,
    schema violation, a processor crash specific to this record).
    TERMINAL PER RECORD: under at-least-once delivery the identical bytes
    re-deliver forever, so retrying is an infinite crash loop — the only
    exits are dropping the record or routing it to a dead-letter topic
    (``resilience.PoisonQuarantine``), after which its offset may retire.
    Transport and broker state are healthy; only this record is not."""


class UnknownTopicError(TpuKafkaError):
    """Topic does not exist on the broker."""


class BarrierError(TpuKafkaError):
    """The pod-wide commit barrier failed.

    The commit path fails *closed* on this: no offsets are committed, so Kafka
    re-delivers the batch — zero uncommitted-batch loss on host preemption.
    """


class CheckpointWireError(TpuKafkaError):
    """A checkpoint frame on the rollout plane failed validation —
    truncated manifest/chunk, CRC mismatch, dtype/shape drift against the
    incumbent tree, or a missing chunk. TERMINAL PER FETCH, never per
    process: the replica rejects the candidate, keeps serving the
    incumbent version, counts the rejection, and a re-published (or
    re-fetched) checkpoint converges — a torn rollout artifact degrades
    the rollout, never the serving path."""


class DistillWireError(TpuKafkaError):
    """A completion frame on the distill topic failed validation — bad
    magic, truncated header/payload, or CRC mismatch. PER RECORD, never
    per trainer: the corpus is at-least-once and self-healing (the
    publisher only ever frames committed tokens), so the trainer drops
    the frame, counts it, and keeps consuming — a torn training record
    costs one sample, never the training loop."""

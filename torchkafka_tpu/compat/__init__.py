"""torch-kafka compatibility surface.

The reference package exports exactly two names
(/root/reference/src/__init__.py:17-18); so does this module. A torch-kafka
user migrates with one import change:

    from torchkafka_tpu.compat import KafkaDataset, auto_commit

(or ``import torchkafka`` via the shim package, keeping their imports
byte-identical). Requires torch; the TPU-native core does not.
"""

from torchkafka_tpu.compat.auto_commit import auto_commit
from torchkafka_tpu.compat.dataset import KafkaDataset

__all__ = ["KafkaDataset", "auto_commit"]

"""auto_commit: the reference's commit orchestrator, re-implemented.

Same three paths as /root/reference/src/auto_commit.py:22-72:

1. non-KafkaDataset dataset -> transparent passthrough (:47-48, the 1.0.1
   capability);
2. ``num_workers == 0`` -> yield the batch, then commit — strictly after the
   caller's loop body for that batch returned (:49-58);
3. multiprocessing -> round-robin over the DataLoader's worker processes,
   signaling worker k to commit after yielding the batch it produced (:59-72).

Path 3 inherits the reference's load-bearing assumption (SURVEY.md §2 quirk
4): torch's _MultiProcessingDataLoaderIter hands out batches round-robin in
``_workers`` order. That holds for stock DataLoaders; a sampler/worker that
reorders batches would signal the wrong worker. It also shares the
reference's coarseness: the worker commits *everything it has polled*, which
may include records already fetched for the next in-flight batch — still
at-least-once, but coarser than batch-exact. The TPU-native path
(torchkafka_tpu.pipeline.KafkaStream) has neither problem: it tracks
batch-exact offsets in an OffsetLedger and needs no worker correspondence.
Prefer it for new code; this module exists for migration parity.
"""

from __future__ import annotations

import itertools
from typing import Any, Iterator

from torch.utils.data import DataLoader

from torchkafka_tpu.compat.dataset import KafkaDataset


def auto_commit(dataloader: DataLoader) -> Iterator[Any]:
    """Iterate a DataLoader, committing each batch's offsets after the
    caller is done with it (yield-then-commit, at-least-once)."""
    if not isinstance(dataloader, DataLoader):
        raise TypeError("A DataLoader must be provided.")

    if not isinstance(dataloader.dataset, KafkaDataset):
        # Regular datasets: behave exactly like iterating the DataLoader.
        yield from dataloader
    elif dataloader.num_workers == 0:
        for batch in dataloader:
            yield batch
            # The caller's loop body has run by the time execution resumes
            # here: commit-after-consumption, the core ordering guarantee.
            dataloader.dataset.commit()
    else:
        # Workers only exist once the iterator is created; we need the
        # iterator object itself to reach their process handles.
        batches = iter(dataloader)
        workers = itertools.cycle(batches._workers)  # noqa: SLF001 - see module docstring
        for worker, batch in zip(workers, batches):
            yield batch
            dataloader.dataset.commit_worker(worker)

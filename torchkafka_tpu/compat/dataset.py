"""Drop-in KafkaDataset: the reference's user API on the TPU-native core.

Re-implements the full public surface of the reference's ``KafkaDataset``
(/root/reference/src/kafka_dataset.py:31-247) — ``_process``, ``new_consumer``,
``placeholder``, ``init_worker``, ``commit``, ``commit_worker``, ``close``,
the platform signal selection, and the dual-mode commit flag protocol — so a
torch-kafka user's subclass and training loop port with an import change.
Built fresh on this framework's Consumer protocol: any transport works
(kafka-python adapter, in-memory broker), and the same dataset class can feed
either a torch DataLoader (this module) or a KafkaStream (the TPU path).

Behavioral contract mirrored, with citations:

- one extension point ``_process(record) -> data | None``; None drops the
  record (:159-162, :173-186)
- auto-commit force-disabled in the consumer factory (:201); never commit on
  close (:89)
- main process: ``commit()`` commits immediately (:103-105); worker process:
  the commit signal only sets a flag (:107-114) and the commit itself runs at
  a known-safe point inside the iteration loop (:164-167 — the 1.1.0
  deadlock fix, CHANGELOG.md:17)
- CommitFailedError is swallowed and logged: records re-deliver (:131-135)
- ``_COMMIT_SIGNAL``: SIGUSR1 on linux, SIGINT on darwin/win (:47-55)

Known reference defects intentionally NOT replicated (SURVEY.md §2):
the broken ``src.`` absolute import (installed-wheel breakage), and the
silent assumption that committing "whatever was polled" equals committing
the yielded batch — documented here loudly instead (see auto_commit).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
from typing import Any

from torch.utils.data import IterableDataset, get_worker_info

from torchkafka_tpu.errors import CommitFailedError
from torchkafka_tpu.source.consumer import Consumer
from torchkafka_tpu.source.kafka import KafkaConsumer

logger = logging.getLogger(__name__)


def _platform_commit_signal() -> signal.Signals:
    # Same mapping as the reference (/root/reference/src/kafka_dataset.py:47-55);
    # raises at class-definition time on unsupported platforms, as it does.
    if sys.platform in ("linux", "linux2"):
        return signal.SIGUSR1
    if sys.platform in ("darwin", "win32", "win64"):
        return signal.SIGINT
    raise RuntimeError(f"Unsupported platform {sys.platform!r}.")


class KafkaDataset(IterableDataset):
    """Streaming dataset over a Kafka-like consumer with manual commits.

    Subclass and implement ``_process``. All constructor arguments flow to
    ``new_consumer`` (the reference's kwargs-passthrough config philosophy,
    SURVEY.md §5); override ``new_consumer`` to change transports or inject
    deserializers (/root/reference/README.md:46-57).
    """

    _COMMIT_SIGNAL = _platform_commit_signal()

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        self._worker_id: int | None = None
        self._commit_required = False
        if kwargs.get("_is_placeholder", False):
            # Placeholder protocol (/root/reference/src/kafka_dataset.py:67-71):
            # consumers are not fork/pickle-safe, so the dataset handed to a
            # multiprocessing DataLoader carries no consumer; each worker
            # builds its own post-fork via init_worker.
            self._consumer: Consumer | None = None
        else:
            if len(args) == 0:
                raise ValueError(
                    "No topic was provided. Use placeholder() to create a "
                    "dataset without a consumer."
                )
            self._consumer = self.new_consumer(*args, **kwargs)

    # ------------------------------------------------------------- teardown

    def __del__(self) -> None:
        self.close()

    def close(self) -> None:
        """Close the consumer WITHOUT committing: uncommitted work must be
        re-delivered (/root/reference/src/kafka_dataset.py:85-91)."""
        # getattr guard: partially-constructed instances lack _consumer.
        if getattr(self, "_consumer", None) is not None:
            self._consumer.close()
        self._commit_required = False

    # --------------------------------------------------------------- commit

    def commit(self, signum: int | None = None, stack: Any = None) -> None:
        """Dual-mode commit (/root/reference/src/kafka_dataset.py:93-118).

        Main process: commit now. Worker process: this is the signal handler —
        it only sets the deferred flag; the commit happens at the next safe
        point in the iteration loop (committing from inside an interrupted
        poll deadlocks — the reference's 1.1.0 fix).
        """
        if self._consumer is None:
            raise RuntimeError("Consumer is not initialized.")
        if self._worker_id is None:
            self._commit_if_required(force=True)
        elif signum is not None:
            if signum != self._COMMIT_SIGNAL:
                raise ValueError(
                    f"Worker {self._worker_id} received a bad signal ({signum})."
                )
            self._commit_required = True
        else:
            raise RuntimeError("Direct commit should not be used with multiprocessing.")

    def _commit_if_required(self, force: bool = False) -> None:
        """Flag-guarded commit; CommitFailedError is non-fatal by contract
        (/root/reference/src/kafka_dataset.py:120-145)."""
        if not force and not self._commit_required:
            return
        who = "" if self._worker_id is None else f" on worker {self._worker_id}"
        try:
            self._consumer.commit()
        except CommitFailedError:
            logger.error("Commit failed%s.", who)
        else:
            logger.debug("Committed offsets%s.", who)
        finally:
            self._commit_required = False

    # ------------------------------------------------------------ iteration

    def __iter__(self):
        """The hot loop (/root/reference/src/kafka_dataset.py:147-171):
        iterate records, transform, drop Nones, honor deferred commits at the
        loop's safe point, restore the signal handler when exhausted."""
        if self._consumer is None:
            raise RuntimeError("Consumer is not initialized.")
        in_worker = self._worker_id is not None
        if in_worker:
            signal.signal(self._COMMIT_SIGNAL, self.commit)
        try:
            for record in self._consumer:
                data = self._process(record)
                if data is not None:
                    yield data
                if in_worker:
                    self._commit_if_required()
        finally:
            if in_worker:
                signal.signal(self._COMMIT_SIGNAL, signal.SIG_DFL)

    def _process(self, record: Any) -> Any:
        """The user extension point: record -> batch element, or None to drop
        (/root/reference/src/kafka_dataset.py:173-186)."""
        raise NotImplementedError()

    # ------------------------------------------------------------ factories

    @classmethod
    def new_consumer(cls, *args: Any, **kwargs: Any) -> Consumer:
        """Consumer factory; force-disables auto-commit — the invariant the
        library exists for (/root/reference/src/kafka_dataset.py:188-206).

        Default transport is the kafka-python adapter (which hard-codes
        ``enable_auto_commit=False``); override in subclasses to use any
        Consumer-protocol transport (e.g. MemoryConsumer for tests).
        """
        if len(args) == 0:
            raise ValueError("Cannot create a consumer without topic.")
        kwargs.pop("_is_placeholder", None)
        # The reference forwards all positional args as topics
        # (/root/reference/src/kafka_dataset.py:206) — multi-topic consumers
        # are valid usage and must keep working.
        return KafkaConsumer(list(args), **kwargs)

    @classmethod
    def init_worker(cls, *args: Any, **kwargs: Any):
        """Build a DataLoader ``worker_init_fn`` that gives each spawned
        worker its own consumer (/root/reference/src/kafka_dataset.py:208-233).

        One consumer per worker process in one consumer group => the broker
        assigns disjoint partitions per worker — the reference's
        data-parallel sharding mechanism.
        """

        def func(worker_id: int) -> None:
            info = get_worker_info()
            if info is None:
                raise RuntimeError(
                    "Custom initialization should be used for multiprocessing only."
                )
            dataset = info.dataset  # the per-worker COPY of the placeholder
            dataset._worker_id = worker_id
            dataset._consumer = cls.new_consumer(*args, **kwargs)

        return func

    @classmethod
    def commit_worker(cls, worker: Any) -> None:
        """Tell a worker process to commit: the cross-process 'commit now'
        RPC, implemented as a POSIX signal
        (/root/reference/src/kafka_dataset.py:235-239)."""
        os.kill(worker.pid, cls._COMMIT_SIGNAL)

    @classmethod
    def placeholder(cls, **kwargs: Any) -> "KafkaDataset":
        """Consumer-less instance for the multiprocessing path
        (/root/reference/src/kafka_dataset.py:241-247). Subclasses with extra
        constructor arguments must override (README.md:62-70)."""
        return cls(_is_placeholder=True, **kwargs)

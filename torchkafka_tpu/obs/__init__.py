"""Record-lifecycle observability (torchkafka_tpu/obs).

The reference has zero instrumentation (PAPER.md §5 tracing row) and the
repo's pre-existing metrics are four counter bags that can say *how many*
but never *where the time went for one record*. This package closes that
gap with three cooperating layers:

- ``trace`` — per-record lifecycle tracing keyed by the identity the whole
  repo already uses, ``(topic, partition, offset)``: typed span events at
  every stage boundary (polled → QoS-admitted → prefill-queued →
  chunk-scheduled → slot-active/first-token → token ticks → finished →
  committed, plus the warm-resume / journal-served / DLQ / deferral
  branches), through an injectable monotonic clock so same-seed chaos
  replays produce identical traces — the repo's differential style applied
  to observability itself. Bounded ring-buffer sink, JSONL export.
- ``slo`` — histograms DERIVED from the trace stream: time-to-first-token,
  inter-token latency, admission queue wait, end-to-end poll→commit,
  labeled by lane / tenant key / replica and pooled fleet-wide with the
  same sample-window merge the commit-latency percentiles use.
- ``burn`` — burn-rate overload detection over the windowed SLO view:
  per-scope error-budget burn over fast/slow trailing windows, a typed
  ok → warning → burning → shedding state machine whose transitions ride
  the trace stream, per-tenant goodput accounting, and the overload hook
  the fleet's AdmissionQueue consumes to prefer deferral over collapse.
- ``exporter`` — one pull-based Prometheus/OpenMetrics HTTP endpoint
  (stdlib ``http.server``, opt-in) exposing every metrics class through
  the shared renderer instead of four ad-hoc ``render_prometheus`` call
  sites.
"""

from torchkafka_tpu.obs.burn import BurnRateMonitor, SLOTarget
from torchkafka_tpu.obs.exporter import MetricsExporter
from torchkafka_tpu.obs.slo import SLOHistograms, pooled_slo_summary
from torchkafka_tpu.obs.trace import (
    STAGES,
    ObsConfig,
    RecordTrace,
    RecordTracer,
    TraceEvent,
)

__all__ = [
    "BurnRateMonitor",
    "MetricsExporter",
    "ObsConfig",
    "RecordTrace",
    "RecordTracer",
    "SLOHistograms",
    "SLOTarget",
    "STAGES",
    "TraceEvent",
    "pooled_slo_summary",
]

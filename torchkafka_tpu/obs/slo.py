"""SLO histograms derived from the record-lifecycle trace stream.

The quantities production serving is judged on — time-to-first-token,
inter-token latency, admission queue wait, end-to-end poll→commit — as
bounded-window percentile histograms labeled along three independent
dimensions: priority lane, tenant key, and replica id (independent
dimensions, not a cross product, matching how the fleet's existing
Prometheus labels are shaped). Built on the same
``utils.metrics.LatencyHistogram`` + pooled-sample-window merge the
commit-latency percentiles use, so a fleet-wide view is percentiles of
the pooled samples, never averages of per-label percentiles.
"""

from __future__ import annotations

import threading

from torchkafka_tpu.utils.metrics import (
    LatencyHistogram,
    merge_latency_summaries,
)

#: The derived latency metrics, in exposition order.
METRICS = ("ttft", "itl", "queue_wait", "e2e")

#: Label dimensions each observation fans into (plus the unlabeled "all").
DIMS = ("lane", "tenant", "replica")


class SLOHistograms:
    """Labeled latency histograms for the four serving SLO quantities.

    Label children are created lazily on first observation — the tenant
    population never needs declaring up front, exactly like the fleet's
    per-tenant counters.

    ``window_s`` (+ the injectable ``clock``) turns every child into a
    time-windowed histogram as well (see ``LatencyHistogram``): a bounded
    ring of per-window sample deltas, so ``windowed_summary(seconds)``
    reports percentiles "over the last S seconds" per metric/label — the
    live view the burn-rate monitor evaluates, next to the cumulative
    one. ``expose_windows`` lists the horizons (seconds) ``series()``
    renders as ``<metric>_window_ms{window="..."}`` Prometheus families."""

    def __init__(self, window: int = 8192, *, window_s: float | None = None,
                 n_windows: int = 16, clock=None,
                 expose_windows: tuple = ()) -> None:
        self._window = window
        self._window_s = window_s
        self._n_windows = n_windows
        self._clock = clock
        if expose_windows and window_s is None:
            raise ValueError("expose_windows requires window_s")
        self.expose_windows = tuple(float(w) for w in expose_windows)
        self._lock = threading.Lock()
        # (metric, dim, label) -> LatencyHistogram; dim "" label "" = all.
        self._h: dict[tuple[str, str, str], LatencyHistogram] = {}

    @property
    def windowed(self) -> bool:
        return self._window_s is not None

    @property
    def window_s(self) -> float | None:
        return self._window_s

    def hist(self, metric: str, dim: str = "", label: str = ""
             ) -> LatencyHistogram:
        if metric not in METRICS:
            raise ValueError(f"metric must be one of {METRICS}, got {metric!r}")
        key = (metric, dim, str(label))
        with self._lock:
            h = self._h.get(key)
            if h is None:
                h = self._h[key] = LatencyHistogram(
                    self._window, window_s=self._window_s,
                    n_windows=self._n_windows, clock=self._clock,
                )
            return h

    def observe(self, metric: str, seconds: float, *, lane=None, tenant=None,
                replica=None) -> None:
        self.hist(metric).observe(seconds)
        if lane is not None:
            self.hist(metric, "lane", lane).observe(seconds)
        if tenant is not None:
            self.hist(metric, "tenant", tenant).observe(seconds)
        if replica is not None:
            self.hist(metric, "replica", replica).observe(seconds)

    def observe_many(self, metric: str, seconds: float, n: int, *,
                     lane=None, tenant=None, replica=None) -> None:
        """``n`` identical samples with ONE label lookup per dimension —
        the inter-token-latency hot path (a K-tick sync surfaces K
        tokens at once; per-sample ``observe`` would pay the dict+lock
        walk K times)."""
        self.hist(metric).observe_many(seconds, n)
        if lane is not None:
            self.hist(metric, "lane", lane).observe_many(seconds, n)
        if tenant is not None:
            self.hist(metric, "tenant", tenant).observe_many(seconds, n)
        if replica is not None:
            self.hist(metric, "replica", replica).observe_many(seconds, n)

    def labels(self, metric: str, dim: str) -> list[str]:
        with self._lock:
            return sorted(
                label for (m, d, label) in self._h if m == metric and d == dim
            )

    def summary(self) -> dict:
        """{metric: {"all": {...}, "by_lane": {...}, "by_tenant": {...},
        "by_replica": {...}}} — each leaf a count/p50_ms/p99_ms dict."""
        out: dict = {}
        for metric in METRICS:
            out[metric] = {"all": self.hist(metric).summary()}
            for dim in DIMS:
                out[metric][f"by_{dim}"] = {
                    label: self.hist(metric, dim, label).summary()
                    for label in self.labels(metric, dim)
                }
        return out

    def windowed_summary(self, seconds: float | None = None) -> dict:
        """Same nested shape as ``summary()`` but over the last
        ``seconds`` only (default: one ``window_s`` bucket) — requires
        time-windowing (``window_s=``)."""
        out: dict = {}
        for metric in METRICS:
            out[metric] = {
                "all": self.hist(metric).windowed_summary(seconds)
            }
            for dim in DIMS:
                out[metric][f"by_{dim}"] = {
                    label: self.hist(metric, dim, label).windowed_summary(
                        seconds
                    )
                    for label in self.labels(metric, dim)
                }
        return out

    def series(self) -> list[tuple]:
        """Exposition series for ``utils.metrics.render_exposition``:
        one ``<metric>_ms`` gauge per SLO quantity with percentile +
        dimension labels, plus the sample-count counters; when
        ``expose_windows`` is set, one ``<metric>_window_ms`` gauge per
        horizon with a ``window`` label (seconds) next to them."""
        from torchkafka_tpu.utils.metrics import format_labels

        out: list[tuple] = []
        for metric in METRICS:
            entries = []
            counts = []
            windowed = []
            all_h = self.hist(metric)
            all_s = all_h.summary()
            for pct in ("p50", "p99"):
                entries.append(
                    (format_labels(percentile=pct), all_s[f"{pct}_ms"])
                )
            counts.append(("", all_s["count"]))
            for horizon in self.expose_windows:
                w = all_h.windowed_summary(horizon)
                for pct in ("p50", "p99"):
                    windowed.append((
                        format_labels(window=f"{horizon:g}",
                                      percentile=pct),
                        w[f"{pct}_ms"],
                    ))
            for dim in DIMS:
                for label in self.labels(metric, dim):
                    h = self.hist(metric, dim, label)
                    s = h.summary()
                    for pct in ("p50", "p99"):
                        entries.append((
                            format_labels(**{dim: label, "percentile": pct}),
                            s[f"{pct}_ms"],
                        ))
                    counts.append(
                        (format_labels(**{dim: label}), s["count"])
                    )
                    for horizon in self.expose_windows:
                        w = h.windowed_summary(horizon)
                        for pct in ("p50", "p99"):
                            windowed.append((
                                format_labels(**{
                                    dim: label, "window": f"{horizon:g}",
                                    "percentile": pct,
                                }),
                                w[f"{pct}_ms"],
                            ))
            help_name = metric.replace("_", " ")
            out.append((
                f"{metric}_ms", "gauge", entries,
                f"{help_name} latency percentiles (ms)",
            ))
            out.append((
                f"{metric}_observations_total", "counter", counts,
                f"{help_name} samples observed",
            ))
            if windowed:
                out.append((
                    f"{metric}_window_ms", "gauge", windowed,
                    f"{help_name} latency percentiles over the trailing "
                    "window (ms)",
                ))
        return out

    def pooled(self, metric: str, dim: str = "", label: str = "") -> dict:
        """Percentile summary of one histogram (sugar over ``hist``)."""
        return self.hist(metric, dim, label).summary()


def pooled_slo_summary(slos: "list[SLOHistograms]") -> dict:
    """Fleet-of-fleets aggregation: pool several SLOHistograms' sample
    windows per (metric, dimension, label) with the same merge the
    commit-latency percentiles use (``merge_latency_summaries`` — a
    tracer with 10× the records weighs 10× the samples)."""
    out: dict = {}
    for metric in METRICS:
        out[metric] = {
            "all": merge_latency_summaries([s.hist(metric) for s in slos])
        }
        for dim in DIMS:
            labels = sorted({
                label for s in slos for label in s.labels(metric, dim)
            })
            out[metric][f"by_{dim}"] = {
                label: merge_latency_summaries(
                    [s.hist(metric, dim, label) for s in slos]
                )
                for label in labels
            }
    return out

"""Per-record lifecycle tracing for the serving path.

One ``RecordTracer`` observes every record's journey through the server
(or a whole fleet — the fleet shares one tracer and tags events with the
replica id) as a stream of typed ``TraceEvent``s keyed by the record's
``(topic, partition, offset)`` identity. Stage boundaries map 1:1 onto
the serving code's own phase transitions:

    polled           note_fetched registered the record with the ledger
    qos_admitted     the QoS admission queue released it to a slot offer
    deferred         paged admission deferred it on block-pool pressure
    prefill_queued   chunked admission reserved a slot + enqueued suffix
    chunk_scheduled  its first suffix tokens rode a fused chunk tick
    warm_resumed     a journal hint restored emitted tokens at admit
    slot_active      first token exists (admit/prefill/activation done)
    tokens           a tick block produced n new tokens for its slot
    finished         generation retired (EOS or max_new), output emitted
    journal_served   finished entry re-served from a dead replica journal
    committed        the offset commit watermark durably covered it
    quarantined      dead-lettered after exhausting its poison budget
    dropped          retired undecodable (no quarantine configured)

Determinism is a design contract, not an accident: the clock is
INJECTABLE (``ObsConfig.clock`` — a ``resilience.ManualClock`` in tests)
and the tracer adds no ordering of its own, so a same-seed chaos replay
yields an identical event sequence (and, under a manual clock, identical
timestamps — byte-identical traces). ``TraceEvent.signature`` is the
timestamp-free tuple the differential tests compare.

Cost discipline: a server built with ``tracer=None`` pays only the
``is not None`` guards at each call site (measured in
benchmarks/bench_obs.py, budgeted ≤ 50 ns/record); an enabled tracer
appends to a bounded ring (``deque(maxlen=...)``) and optionally streams
JSONL. Derived SLO histograms (obs/slo.py) update inline on the events
that close a latency interval.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Callable, Iterable, NamedTuple

from torchkafka_tpu.obs.slo import SLOHistograms
from torchkafka_tpu.source.records import Record

POLLED = "polled"
QOS_ADMITTED = "qos_admitted"
DEFERRED = "deferred"
PREFILL_QUEUED = "prefill_queued"
CHUNK_SCHEDULED = "chunk_scheduled"
WARM_RESUMED = "warm_resumed"
SLOT_ACTIVE = "slot_active"
TOKENS = "tokens"
FINISHED = "finished"
JOURNAL_SERVED = "journal_served"
COMMITTED = "committed"
QUARANTINED = "quarantined"
DROPPED = "dropped"
# A dead-letter produce FAILED: the record's quarantine copy is NOT
# durable. Terminal observability for the swallowed-DLQ path (the
# stream's guard logs and continues by contract; this event + the
# dlq_delivery_failures counter are what make a broken DLQ visible on
# the trace stream and /metrics instead of stderr only). Not part of
# the happy lifecycle: the record stays open/unresolved.
DLQ_FAILED = "dlq_failed"
# Disaggregated prefill (fleet/prefill.py + serve.py adoption): a
# PREFILL worker published the record's filled-KV handoff onto the
# transfer plane, and a DECODE replica adopted it into a slot without
# running a prompt pass. Together with PREFILL_QUEUED these spell the
# disaggregated admission lifecycle: prefill_queued → handoff → adopted.
PREFILL_HANDOFF = "handoff"
SLOT_ADOPTED = "adopted"
# Not a record stage: a BurnRateMonitor state transition, riding the
# same event stream (topic "slo", offset = transition sequence) so
# overload state changes land in the trace, ordered against the record
# lifecycles that caused them — and replay byte-identically.
BURN_STATE = "burn_state"
# Membership events (topic "fleet", offset = membership sequence): the
# fleet's liveness story on the same stream — a replica joining the
# group, a replica fenced (lease expiry, kill, drain-timeout), and a
# dead replica's journal handed to survivors — ordered against the
# record lifecycles they interrupt or resume.
REPLICA_JOINED = "replica_joined"
REPLICA_FENCED = "replica_fenced"
JOURNAL_HANDOFF = "journal_handoff"
# The broker itself died and was crash-recovered from its write-ahead
# log (ProcessFleet.restart_broker): the one event that interrupts EVERY
# record lifecycle at once, so it rides the same "fleet" stream ordered
# against them.
BROKER_RESTARTED = "broker_restarted"
# An autoscale controller decision (fleet/autoscale.py): the control
# plane's actuation orders ride the "fleet" stream ordered against the
# joins/drains/fences they cause — under a ManualClock the whole control
# loop (load → burn transitions → decisions → scale events) replays
# byte-identically.
SCALE_DECISION = "scale_decision"
# The live model lifecycle (fleet/rollout.py): the rollout state machine
# (pending → canary → rolling → complete | rolled_back) typed on the
# "fleet" stream, ordered against the record lifecycles a swap pauses
# and the fences a stale-version zombie earns. ``rollout_phase`` marks
# every controller phase transition; ``canary_started`` opens the
# shadow-serving slice; ``swapped`` is one replica's atomic weight
# rebind landing (also emitted by the server itself at swap_params);
# ``rolled_back`` is the automatic verdict on a divergent canary.
ROLLOUT_PHASE = "rollout_phase"
CANARY_STARTED = "canary_started"
SWAPPED = "swapped"
ROLLED_BACK = "rolled_back"
# Online draft distillation (torchkafka_tpu/distill): the closed loop's
# control decisions on the same "fleet" stream. ``draft_refresh`` is the
# DistillController's verdict (the windowed live-α crossed the refresh
# gate, or a refresh was rejected — the reason attribute says which);
# ``draft_swapped`` is one replica's draft rebinding landing between
# ticks (no quiesce — the draft only proposes, verification commits).
# Under a ManualClock the whole loop replays byte-identically.
DRAFT_REFRESH = "draft_refresh"
DRAFT_SWAPPED = "draft_swapped"

STAGES = (
    POLLED, QOS_ADMITTED, DEFERRED, PREFILL_QUEUED, CHUNK_SCHEDULED,
    WARM_RESUMED, SLOT_ACTIVE, TOKENS, FINISHED, JOURNAL_SERVED, COMMITTED,
    QUARANTINED, DROPPED, DLQ_FAILED, PREFILL_HANDOFF, SLOT_ADOPTED,
    BURN_STATE, REPLICA_JOINED, REPLICA_FENCED, JOURNAL_HANDOFF,
    SCALE_DECISION, ROLLOUT_PHASE, CANARY_STARTED, SWAPPED, ROLLED_BACK,
    DRAFT_REFRESH, DRAFT_SWAPPED,
)


def _default_tenant(record: Record) -> str:
    """Tenant = the record key (Kafka's partitioning identity) — the same
    rule fleet/qos.py admits by, duplicated here so the tracer needs no
    QoS layer to label a bare StreamingGenerator's traffic."""
    if record.key is None:
        return "anon"
    try:
        return record.key.decode("utf-8")
    except UnicodeDecodeError:
        return record.key.hex()


def _default_lane(record: Record) -> str:
    for k, v in record.headers:
        if k == "lane":
            return "interactive" if v == b"interactive" else "batch"
    return "batch"


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Tracing policy for a server or fleet.

    ``clock``: the monotonic clock every event timestamp reads (None =
    ``time.monotonic``); inject a ``ManualClock.now`` and traces become
    byte-identical across same-seed replays. ``capacity``: ring-buffer
    bound — streams may run forever, traces must not. ``jsonl_path``:
    when set, every event is ALSO appended to this file as one JSON line
    at emit time (offline analysis; the measured-cost tier above the
    ring). ``token_events``: emit per-tick ``tokens`` events (the ITL
    source); off keeps only stage-boundary events for long soaks.

    ``window_s``: bucket width (seconds) for the TIME-windowed SLO view
    (obs/slo.py): percentiles "over the last S seconds" next to the
    cumulative ones — required by a ``BurnRateMonitor``. ``n_windows``
    bounds the delta ring; ``expose_windows`` lists horizons the
    Prometheus exposition renders (default: one ``window_s``)."""

    capacity: int = 65536
    clock: Callable[[], float] | None = None
    jsonl_path: str | None = None
    token_events: bool = True
    tenant_of: Callable[[Record], str] = _default_tenant
    lane_of: Callable[[Record], str] = _default_lane
    window_s: float | None = None
    n_windows: int = 16
    expose_windows: tuple = ()

    def __post_init__(self) -> None:
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if self.window_s is not None and self.window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {self.window_s}")


class TraceEvent(NamedTuple):
    """One typed span event. ``t`` is the injected clock's reading at
    emit; ``attrs`` is a sorted (key, value) tuple so events hash/compare
    deterministically. A NamedTuple, not a dataclass: the constructor is
    on the per-event hot path and tuple construction is ~5× cheaper."""

    stage: str
    topic: str
    partition: int
    offset: int
    t: float
    attrs: tuple = ()

    @property
    def key(self) -> tuple[str, int, int]:
        return (self.topic, self.partition, self.offset)

    @property
    def signature(self) -> tuple:
        """Everything but the timestamp — what same-seed replay
        differentials compare (wall clocks differ, lifecycles must not)."""
        return (self.stage, self.topic, self.partition, self.offset,
                self.attrs)

    def to_json(self) -> dict:
        d = {
            "stage": self.stage, "topic": self.topic, "p": self.partition,
            "o": self.offset, "t": self.t,
        }
        d.update(dict(self.attrs))
        return d


@dataclasses.dataclass
class RecordTrace:
    """One record's ordered lifecycle view (``RecordTracer.record_trace``)
    with the derived per-record latencies the SLO histograms aggregate."""

    topic: str
    partition: int
    offset: int
    events: list[TraceEvent]

    def _t(self, stage: str) -> float | None:
        for e in self.events:
            if e.stage == stage:
                return e.t
        return None

    def stages(self) -> list[str]:
        return [e.stage for e in self.events]

    @property
    def queue_wait_s(self) -> float | None:
        """poll → QoS admission (None when no QoS layer ran)."""
        t0, t1 = self._t(POLLED), self._t(QOS_ADMITTED)
        return None if t0 is None or t1 is None else max(0.0, t1 - t0)

    @property
    def ttft_s(self) -> float | None:
        """poll → first token (admission + queue + prefill, inclusive)."""
        t0, t1 = self._t(POLLED), self._t(SLOT_ACTIVE)
        return None if t0 is None or t1 is None else max(0.0, t1 - t0)

    @property
    def e2e_s(self) -> float | None:
        """poll → durable offset commit."""
        t0, t1 = self._t(POLLED), self._t(COMMITTED)
        return None if t0 is None or t1 is None else max(0.0, t1 - t0)

    @property
    def itl_s(self) -> list[float]:
        """Per-token inter-token latencies, at host-sync granularity: a
        ``tokens`` event carrying n tokens spreads its interval over n."""
        out: list[float] = []
        prev = self._t(SLOT_ACTIVE)
        for e in self.events:
            if e.stage != TOKENS or prev is None:
                continue
            n = dict(e.attrs).get("n", 1)
            out.extend([max(0.0, e.t - prev) / max(1, n)] * n)
            prev = e.t
        return out


class _Lifecycle:
    """Open per-record state between POLLED and a terminal stage."""

    __slots__ = ("lane", "tenant", "replica", "polled_t", "active_t",
                 "last_tok_t", "finished", "tokens", "warm", "queue_wait")

    def __init__(self, lane: str, tenant: str, replica, t: float) -> None:
        self.lane = lane
        self.tenant = tenant
        self.replica = replica
        self.polled_t = t
        self.active_t: float | None = None
        self.last_tok_t: float | None = None
        self.finished = False
        self.tokens = 0
        self.warm = False  # first token predates this poll (warm resume)
        self.queue_wait: float | None = None


class RecordTracer:
    """The lifecycle tracer: emit-side API for the serving code, read-side
    API (ring, per-record views, SLO summaries, Prometheus) for
    operators and tests. Thread-safe (one lock around ring + lifecycle
    state); the cooperative fleet scheduler never contends it."""

    def __init__(self, config: ObsConfig | None = None, **kw) -> None:
        self.config = config or ObsConfig(**kw)
        self._clock = self.config.clock or time.monotonic
        self._lock = threading.Lock()
        self.events: deque[TraceEvent] = deque(maxlen=self.config.capacity)
        self.dropped_events = 0  # emitted beyond the ring's capacity
        self._emitted = 0
        self._open: dict[tuple[str, int, int], _Lifecycle] = {}
        cfg = self.config
        self.slo = SLOHistograms(
            window_s=cfg.window_s, n_windows=cfg.n_windows,
            clock=self._clock,
            expose_windows=cfg.expose_windows or (
                (cfg.window_s,) if cfg.window_s is not None else ()
            ),
        )
        # Optional obs.BurnRateMonitor: receives per-completion goodput
        # classifications (note_commit) and quarantine events.
        self._monitor = None
        self._membership_seq = 0  # offsets for topic-"fleet" events
        self._jsonl = None
        if self.config.jsonl_path is not None:
            self._jsonl = open(self.config.jsonl_path, "a", encoding="utf-8")

    def attach_monitor(self, monitor) -> None:
        """Bind a ``BurnRateMonitor``: committed lifecycles feed its
        goodput ledger, and its state transitions ride this tracer's
        event stream (``burn_state``)."""
        self._monitor = monitor

    # -------------------------------------------------------------- emit

    def _emit(self, stage: str, topic: str, partition: int, offset: int,
              attrs: tuple) -> float:
        t = self._clock()
        ev = TraceEvent(stage, topic, partition, offset, t, attrs)
        if len(self.events) == self.events.maxlen:
            self.dropped_events += 1
        self.events.append(ev)
        self._emitted += 1
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(ev.to_json()) + "\n")
        return t

    def _life(self, rec: Record, replica) -> _Lifecycle:
        key = (rec.topic, rec.partition, rec.offset)
        life = self._open.get(key)
        if life is None:
            # Tolerate a mid-lifecycle start (tracer attached late, or an
            # event arriving before its POLLED — e.g. a journal-served
            # completion admitted straight from a hint).
            life = _Lifecycle(
                self.config.lane_of(rec), self.config.tenant_of(rec),
                replica, self._clock(),
            )
            self._open[key] = life
        return life

    def polled(self, rec: Record, replica=None) -> None:
        with self._lock:
            lane = self.config.lane_of(rec)
            tenant = self.config.tenant_of(rec)
            t = self._emit(POLLED, rec.topic, rec.partition, rec.offset, (
                ("lane", lane), ("replica", replica), ("tenant", tenant),
            ))
            # Redelivery restarts the lifecycle (the first incarnation's
            # interval died with its replica).
            self._open[(rec.topic, rec.partition, rec.offset)] = _Lifecycle(
                lane, tenant, replica, t
            )

    def qos_admitted(self, rec: Record, lane: str, wait_s: float,
                     replica=None) -> None:
        with self._lock:
            life = self._life(rec, replica)
            life.replica = replica if replica is not None else life.replica
            self._emit(QOS_ADMITTED, rec.topic, rec.partition, rec.offset, (
                ("lane", lane), ("replica", replica),
            ))
            life.queue_wait = max(0.0, wait_s)
            self.slo.observe(
                "queue_wait", life.queue_wait, lane=lane,
                tenant=life.tenant, replica=life.replica,
            )

    def deferred(self, rec: Record, replica=None) -> None:
        with self._lock:
            self._emit(DEFERRED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))

    def prefill_queued(self, rec: Record, suffix_tokens: int,
                       replica=None) -> None:
        with self._lock:
            self._emit(PREFILL_QUEUED, rec.topic, rec.partition, rec.offset, (
                ("replica", replica), ("suffix_tokens", suffix_tokens),
            ))

    def chunk_scheduled(self, rec: Record, replica=None) -> None:
        with self._lock:
            self._emit(CHUNK_SCHEDULED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))

    def prefill_handoff(self, rec: Record, blocks: int, replica=None) -> None:
        """A PREFILL worker published this record's filled-KV handoff on
        the transfer plane (``blocks`` prompt blocks of payload)."""
        with self._lock:
            self._emit(PREFILL_HANDOFF, rec.topic, rec.partition, rec.offset, (
                ("blocks", blocks), ("replica", replica),
            ))

    def adopted(self, rec: Record, replica=None) -> None:
        """A DECODE replica adopted this record's handoff into a slot —
        no prompt pass ran here; the follow-up ``slot_active`` closes
        TTFT as usual (the first token genuinely exists now)."""
        with self._lock:
            self._emit(SLOT_ADOPTED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))

    def warm_resumed(self, rec: Record, tokens_restored: int,
                     replica=None) -> None:
        with self._lock:
            self._emit(WARM_RESUMED, rec.topic, rec.partition, rec.offset, (
                ("replica", replica), ("tokens_restored", tokens_restored),
            ))

    def slot_active(self, rec: Record, replica=None, warm: bool = False) -> None:
        """First token exists for this record: admit dispatch returned
        (dense / legacy-paged) or the activation chunk tick landed
        (chunked). Closes the TTFT interval."""
        with self._lock:
            life = self._life(rec, replica)
            life.replica = replica if replica is not None else life.replica
            t = self._emit(SLOT_ACTIVE, rec.topic, rec.partition, rec.offset, (
                ("replica", replica), ("warm", warm),
            ))
            life.active_t = t
            life.last_tok_t = t
            life.tokens = max(life.tokens, 1)
            life.warm = warm
            if not warm:
                # A warm resume's "first token" was decoded by the dead
                # replica pre-kill; timing it from THIS poll would report
                # a fabricated (and negative-looking) TTFT.
                self.slo.observe(
                    "ttft", max(0.0, t - life.polled_t), lane=life.lane,
                    tenant=life.tenant, replica=life.replica,
                )

    def tokens(self, rec: Record, n_new: int, replica=None) -> None:
        """A tick block surfaced ``n_new`` new tokens for this record
        (host-sync granularity: with ticks_per_sync=K, K tokens arrive
        per event and the interval is spread over them)."""
        if n_new <= 0:
            return
        with self._lock:
            life = self._life(rec, replica)
            if self.config.token_events:
                self._emit(TOKENS, rec.topic, rec.partition, rec.offset, (
                    ("n", n_new), ("replica", replica),
                ))
            if life.last_tok_t is not None:
                per_tok = max(0.0, self._clock() - life.last_tok_t) / n_new
                self.slo.observe_many(
                    "itl", per_tok, n_new, lane=life.lane,
                    tenant=life.tenant, replica=life.replica,
                )
            life.last_tok_t = self._clock()
            life.tokens += n_new

    def finished(self, rec: Record, n_tokens: int, replica=None) -> None:
        with self._lock:
            life = self._life(rec, replica)
            life.finished = True
            self._emit(FINISHED, rec.topic, rec.partition, rec.offset, (
                ("replica", replica), ("tokens", n_tokens),
            ))

    def journal_served(self, rec: Record, n_tokens: int, replica=None) -> None:
        with self._lock:
            life = self._life(rec, replica)
            life.finished = True
            self._emit(JOURNAL_SERVED, rec.topic, rec.partition, rec.offset, (
                ("replica", replica), ("tokens", n_tokens),
            ))

    def quarantined(self, rec: Record, replica=None) -> None:
        with self._lock:
            self._emit(QUARANTINED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))
            self._open.pop((rec.topic, rec.partition, rec.offset), None)
            if self._monitor is not None:
                self._monitor.note_quarantined(self.config.tenant_of(rec))

    def dropped(self, rec: Record, replica=None) -> None:
        with self._lock:
            self._emit(DROPPED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))
            self._open.pop((rec.topic, rec.partition, rec.offset), None)

    def dlq_failed(self, rec: Record, replica=None) -> None:
        """A dead-letter produce for ``rec`` failed — the quarantine copy
        is NOT durable. The record's lifecycle stays OPEN (it is neither
        served, dropped, nor durably quarantined), which is exactly what
        the trace should say about it."""
        with self._lock:
            self._emit(DLQ_FAILED, rec.topic, rec.partition, rec.offset,
                       (("replica", replica),))

    def note_commit(self, snapshot: dict) -> None:
        """A successful offset commit: every FINISHED lifecycle whose
        offset the committed next-read watermark covers becomes
        COMMITTED (closing the e2e interval) and its state retires —
        exactly the ledger's own durability rule, so the trace can never
        claim a commit the broker did not make."""
        if not snapshot or not self._open:
            return
        with self._lock:
            done = [
                (key, life) for key, life in self._open.items()
                if life.finished
                and key[2] < snapshot.get((key[0], key[1]), -1)
            ]
            for (topic, partition, offset), life in done:
                t = self._emit(COMMITTED, topic, partition, offset,
                               (("replica", life.replica),))
                e2e = max(0.0, t - life.polled_t)
                self.slo.observe(
                    "e2e", e2e, lane=life.lane,
                    tenant=life.tenant, replica=life.replica,
                )
                if self._monitor is not None:
                    ttft = (
                        None
                        if life.warm or life.active_t is None
                        else max(0.0, life.active_t - life.polled_t)
                    )
                    self._monitor.note_completed(
                        life.lane, life.tenant, ttft_s=ttft, e2e_s=e2e,
                        queue_wait_s=life.queue_wait,
                    )
                del self._open[(topic, partition, offset)]

    def replica_joined(self, member: str, replica=None) -> None:
        """A replica became a live group member (spawned, respawned, or
        scaled in). Topic ``fleet``; offset = membership sequence."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(REPLICA_JOINED, "fleet", 0, seq, (
                ("member", member), ("replica", replica),
            ))

    def replica_fenced(self, member: str, reason: str = "lease_expired",
                       lease_age_s: float | None = None,
                       replica=None) -> None:
        """A replica was fenced out of the group: its lease expired (a
        real process death — or a zombie too slow to renew), it was
        killed, or it overran a drain timeout. Its partitions rebalance
        to survivors; its stale-generation commits are rejected from
        here on."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            attrs = [("member", member), ("reason", reason),
                     ("replica", replica)]
            if lease_age_s is not None:
                attrs.append(("lease_age_s", round(lease_age_s, 4)))
            self._emit(REPLICA_FENCED, "fleet", 0, seq,
                       tuple(sorted(attrs)))

    def journal_handoff(self, member: str, entries: int,
                        replica=None) -> None:
        """A dead replica's on-disk decode journal was handed to
        survivors (``entries`` live generations become warm-resume
        hints)."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(JOURNAL_HANDOFF, "fleet", 0, seq, (
                ("entries", entries), ("member", member),
                ("replica", replica),
            ))

    def broker_restarted(self, replayed_records: int = 0,
                         aborted_txns: int = 0,
                         recovery_ms: float = 0.0) -> None:
        """The hosted broker was crash-recovered from its WAL: how much
        state the log salvaged (records replayed, dangling transactions
        aborted) and how long the replay took. Topic ``fleet``; offset =
        membership sequence — ordered against the joins/fences the
        outage may have triggered."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(BROKER_RESTARTED, "fleet", 0, seq, (
                ("aborted_txns", aborted_txns),
                ("recovery_ms", round(recovery_ms, 3)),
                ("replayed_records", replayed_records),
            ))

    def scale_decision(self, role: str, direction: str, reason: str,
                       frm: int, to: int) -> None:
        """An autoscale controller moved ``role``'s target replica count
        ``frm`` → ``to`` (``direction`` up/down) because ``reason``
        (burn / queue / idle). Topic ``fleet``; offset = membership
        sequence — ordered against the joins and drains it causes."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(SCALE_DECISION, "fleet", 0, seq, (
                ("direction", direction), ("from", frm),
                ("reason", reason), ("role", role), ("to", to),
            ))

    def rollout_phase(self, phase: str, version: int) -> None:
        """The rollout controller entered ``phase`` for target
        ``version``. Topic ``fleet``; offset = membership sequence —
        ordered against the swaps, fences, and joins the phase drives."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(ROLLOUT_PHASE, "fleet", 0, seq, (
                ("phase", phase), ("version", int(version)),
            ))

    def canary_started(self, member: str, version: int,
                       slice_n: int | None = None) -> None:
        """Member ``member`` began shadow-serving a deterministic slice
        under candidate ``version`` — token-diffed against the incumbent
        before any weight anywhere is swapped."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            attrs = [("member", member), ("version", int(version))]
            if slice_n is not None:
                attrs.append(("slice_n", int(slice_n)))
            self._emit(CANARY_STARTED, "fleet", 0, seq,
                       tuple(sorted(attrs)))

    def swapped(self, version: int, member: str | None = None,
                replica=None) -> None:
        """One replica's weights atomically rebound to ``version`` (the
        drain-swap landed: in-flight finished, window committed, journal
        meta flipped, params swapped without recompiling)."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            attrs = [("version", int(version))]
            if member is not None:
                attrs.append(("member", member))
            if replica is not None:
                attrs.append(("replica", replica))
            self._emit(SWAPPED, "fleet", 0, seq, tuple(sorted(attrs)))

    def rolled_back(self, reason: str, version: int) -> None:
        """The rollout of ``version`` was automatically halted and every
        swapped replica ordered back to the incumbent (``reason``:
        canary_divergence / checkpoint_rejected / ...)."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            self._emit(ROLLED_BACK, "fleet", 0, seq, (
                ("reason", reason), ("version", int(version)),
            ))

    def draft_refresh(self, reason: str, version: int,
                      alpha: float | None = None) -> None:
        """The DistillController decided a draft refresh (``reason``:
        alpha_drop / forced) or rejected one (checkpoint_rejected).
        α rounded so the JSONL trace round-trips byte-exact."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            attrs = [("reason", reason), ("version", int(version))]
            if alpha is not None:
                attrs.append(("alpha", round(float(alpha), 4)))
            self._emit(DRAFT_REFRESH, "fleet", 0, seq,
                       tuple(sorted(attrs)))

    def draft_swapped(self, version: int, member: str | None = None,
                      replica=None) -> None:
        """One replica's DRAFT rebound to checkpoint ``version`` between
        ticks — committed tokens unchanged by contract (the draft only
        proposes; the target's verification commits)."""
        with self._lock:
            seq = self._membership_seq
            self._membership_seq += 1
            attrs = [("version", int(version))]
            if member is not None:
                attrs.append(("member", member))
            if replica is not None:
                attrs.append(("replica", replica))
            self._emit(DRAFT_SWAPPED, "fleet", 0, seq,
                       tuple(sorted(attrs)))

    def burn_state(self, seq: int, metric: str, dim: str, label: str,
                   old: str, new: str, fast: float, slow: float) -> None:
        """A BurnRateMonitor state transition as a typed event on the
        shared stream: topic ``slo``, offset = the monitor's transition
        sequence, burn rates rounded so JSONL round-trips byte-exact."""
        with self._lock:
            self._emit(BURN_STATE, "slo", 0, seq, (
                ("dim", dim), ("fast", round(fast, 4)), ("from", old),
                ("label", label), ("metric", metric),
                ("slow", round(slow, 4)), ("to", new),
            ))

    # -------------------------------------------------------------- read

    def __len__(self) -> int:
        return len(self.events)

    @property
    def emitted(self) -> int:
        """Total events emitted (ring may retain fewer)."""
        return self._emitted

    def signature(self) -> list[tuple]:
        """The retained events' timestamp-free signatures, in order — the
        unit of comparison for same-seed replay differentials."""
        with self._lock:
            return [e.signature for e in self.events]

    def record_trace(self, topic: str, partition: int, offset: int
                     ) -> RecordTrace:
        with self._lock:
            evs = [e for e in self.events
                   if e.key == (topic, partition, offset)]
        return RecordTrace(topic, partition, offset, evs)

    def export_jsonl(self, path: str) -> int:
        """Dump the retained ring to ``path`` (one event per line);
        returns the number of events written. Offline-analysis companion
        to the streaming ``jsonl_path`` sink."""
        with self._lock:
            evs = list(self.events)
        with open(path, "w", encoding="utf-8") as f:
            for e in evs:
                f.write(json.dumps(e.to_json()) + "\n")
        return len(evs)

    @staticmethod
    def load_jsonl(path: str) -> list[TraceEvent]:
        out = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                d = json.loads(line)
                attrs = tuple(sorted(
                    (k, v) for k, v in d.items()
                    if k not in ("stage", "topic", "p", "o", "t")
                ))
                out.append(TraceEvent(
                    d["stage"], d["topic"], d["p"], d["o"], d["t"], attrs
                ))
        return out

    def summary(self) -> dict:
        with self._lock:
            stages: dict[str, int] = {}
            for e in self.events:
                stages[e.stage] = stages.get(e.stage, 0) + 1
            open_records = len(self._open)
        return {
            "events": self._emitted,
            "retained": len(self.events),
            "ring_dropped": self.dropped_events,
            "open_records": open_records,
            "stages": stages,
            "slo": self.slo.summary(),
        }

    def render_prometheus(self, prefix: str = "torchkafka_slo") -> str:
        """The SLO histograms plus the tracer's own health counters,
        through the shared exposition renderer."""
        from torchkafka_tpu.utils.metrics import render_exposition

        series = [
            ("trace_events_total", "counter", self._emitted,
             "lifecycle trace events emitted"),
            ("trace_ring_dropped_total", "counter", self.dropped_events,
             "events evicted from the bounded ring"),
            ("trace_open_records", "gauge", len(self._open),
             "records with an open (uncommitted) lifecycle"),
        ]
        series.extend(self.slo.series())
        return render_exposition(prefix, series)

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None

    def __enter__(self) -> "RecordTracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def events_signature(events: Iterable[TraceEvent]) -> list[tuple]:
    """Timestamp-free signature of an arbitrary event list (e.g. one
    loaded back from JSONL)."""
    return [e.signature for e in events]

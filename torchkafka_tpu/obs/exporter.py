"""One pull-based Prometheus/OpenMetrics endpoint for every metrics class.

Before this module each metrics set (``StreamMetrics``, ``ServeMetrics``,
``FleetMetrics``, ``ResilienceMetrics``, the SLO tracer) had its own
``render_prometheus`` and no transport — operators had to wire their own
scrape path per class. ``MetricsExporter`` registers any number of
sources and serves their concatenated expositions from a single stdlib
``http.server`` endpoint (opt-in, daemon thread, ephemeral port by
default so tests never collide):

    exporter = MetricsExporter()
    exporter.add(stream.metrics)                       # any render_prometheus
    exporter.add(lambda: fleet.metrics.render_prometheus(
        replicas=fleet.replicas))                      # or a callable
    exporter.add(tracer)                               # the SLO tracer
    exporter.start()
    # curl http://127.0.0.1:{exporter.port}/metrics

No new dependencies: the exposition text format is what the shared
renderer (``utils.metrics.render_exposition``) already produces, and the
conformance test in tests/test_obs.py pins every source to it.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

_logger = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """Aggregates metric sources and serves GET /metrics.

    A *source* is anything with a zero-argument ``render_prometheus()``
    method, or a zero-argument callable returning exposition text (use a
    lambda to bind arguments, e.g. FleetMetrics' ``replicas=``). Sources
    render at scrape time — no caching — and a raising source is skipped
    with a comment line rather than failing the whole scrape (one broken
    metrics class must not blind the operator to the others)."""

    def __init__(self, sources=(), *, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self._sources: list[Callable[[], str]] = []
        for s in sources:
            self.add(s)
        self._host = host
        self._port = port
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def add(self, source) -> "MetricsExporter":
        render = getattr(source, "render_prometheus", None)
        if render is None:
            if not callable(source):
                raise TypeError(
                    "exporter sources need a render_prometheus() method or "
                    f"must be zero-arg callables, got {type(source).__name__}"
                )
            render = source
        self._sources.append(render)
        return self

    def render(self) -> str:
        """The concatenated exposition of every registered source."""
        parts = []
        for render in self._sources:
            try:
                text = render()
            except Exception as exc:  # noqa: BLE001 - scrape must survive
                _logger.exception("metrics source failed to render")
                parts.append(f"# source error: {type(exc).__name__}\n")
                continue
            if text and not text.endswith("\n"):
                text += "\n"
            parts.append(text)
        return "".join(parts)

    # ---------------------------------------------------------------- http

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("exporter not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}/metrics"

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib contract
                if self.path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # quiet scrapes
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tk-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

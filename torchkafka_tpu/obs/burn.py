"""Burn-rate overload detection over the windowed SLO histograms.

The question PR 7's cumulative histograms cannot answer — "is the SLO
burning RIGHT NOW, and should we shed load?" — answered the way SRE
practice does: an **error budget** (1 - objective: the fraction of
requests allowed to miss the latency threshold) and a **burn rate** (the
observed violation fraction divided by that budget) evaluated over a
FAST and a SLOW trailing window. Fast-window burn reacts in seconds;
requiring the slow window to agree before escalating keeps a one-burst
blip from flapping the state machine — the classic multiwindow
multi-burn-rate alerting shape, run in-process so admission can consume
it instead of a human pager.

Per ``SLOTarget`` the monitor tracks one state machine per scope — the
global stream plus every lane and tenant label the SLO histograms have
seen — through four typed states::

    ok → warning → burning → shedding   (and back down as windows drain)

Every transition is emitted as a typed ``burn_state`` event through the
record tracer's stream (same ring, same JSONL, same determinism contract:
under a ManualClock a same-seed replay produces byte-identical
transitions), and the current state is consumed by the fleet's
``AdmissionQueue`` as an overload hook: in ``shedding``, batch-lane
admission is DEFERRED (records stay queued, watermark stalled — the
at-least-once contract untouched) so interactive traffic keeps its SLO
instead of the whole fleet collapsing together.

The monitor also owns **goodput accounting**: a completion is *goodput*
only if it met every configured latency target — the per-tenant
completed / completed-within-SLO / deferred / quarantined ledger that
turns "throughput" into the number production actually buys.
"""

from __future__ import annotations

import dataclasses
import threading

from torchkafka_tpu.obs.slo import SLOHistograms

OK = "ok"
WARNING = "warning"
BURNING = "burning"
SHEDDING = "shedding"

STATES = (OK, WARNING, BURNING, SHEDDING)
STATE_LEVEL = {s: i for i, s in enumerate(STATES)}


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One latency objective to monitor.

    ``metric``: which SLO quantity (obs.slo.METRICS). ``threshold_s``:
    the latency bound a sample must meet. ``objective``: the fraction of
    samples that must meet it (error budget = 1 - objective).
    ``fast_window_s``/``slow_window_s``: the two trailing evaluation
    horizons. ``warn_burn``/``burning_burn``/``shed_burn``: burn-rate
    ladder — warn on fast alone, escalate only when the slow window
    agrees. ``lane``: restrict this target to one lane's label scope
    (None = monitor every scope the histograms have seen).
    ``min_samples``: a window with fewer samples reads burn 0 (no
    evidence is not an emergency)."""

    metric: str = "ttft"
    threshold_s: float = 0.1
    objective: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    warn_burn: float = 1.0
    burning_burn: float = 2.0
    shed_burn: float = 4.0
    lane: str | None = None
    min_samples: int = 4

    def __post_init__(self) -> None:
        from torchkafka_tpu.obs.slo import METRICS

        if self.metric not in METRICS:
            raise ValueError(
                f"metric must be one of {METRICS}, got {self.metric!r}"
            )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must sit in (0, 1), got {self.objective}"
            )
        if self.threshold_s <= 0:
            raise ValueError(f"threshold_s must be > 0, got {self.threshold_s}")
        if not 0 < self.fast_window_s <= self.slow_window_s:
            raise ValueError(
                "need 0 < fast_window_s <= slow_window_s, got "
                f"{self.fast_window_s}/{self.slow_window_s}"
            )
        if not 0 < self.warn_burn <= self.burning_burn <= self.shed_burn:
            raise ValueError(
                "need 0 < warn_burn <= burning_burn <= shed_burn, got "
                f"{self.warn_burn}/{self.burning_burn}/{self.shed_burn}"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


class _Goodput:
    """One tenant's goodput ledger (counts; rates live on FleetMetrics)."""

    __slots__ = ("completed", "within_slo", "quarantined")

    def __init__(self) -> None:
        self.completed = 0
        self.within_slo = 0
        self.quarantined = 0


class BurnRateMonitor:
    """Evaluates ``SLOTarget``s against windowed ``SLOHistograms``.

    ``evaluate()`` is cheap and idempotent between new samples; a traced
    fleet calls it once per scheduling round. ``tracer`` (optional)
    receives typed ``burn_state`` transition events; ``should_defer`` is
    the AdmissionQueue's overload hook. Thread-safe; deterministic under
    a ManualClock (sorted scope iteration, transition-only emission)."""

    def __init__(
        self,
        slo: SLOHistograms,
        targets: "list[SLOTarget] | tuple[SLOTarget, ...]",
        *,
        tracer=None,
        shed_lanes: tuple = ("batch",),
    ) -> None:
        if not targets:
            raise ValueError("BurnRateMonitor needs at least one SLOTarget")
        if not slo.windowed:
            raise ValueError(
                "BurnRateMonitor needs time-windowed SLO histograms — "
                "build the tracer with ObsConfig(window_s=...)"
            )
        self.slo = slo
        self.targets = tuple(targets)
        self.tracer = tracer
        self._shed_lanes = frozenset(shed_lanes)
        self._lock = threading.Lock()
        # (metric, dim, label) -> state string.
        self._state: dict[tuple[str, str, str], str] = {}
        # (metric, dim, label) -> (fast_burn, slow_burn), last evaluate().
        self._burn: dict[tuple[str, str, str], tuple[float, float]] = {}
        self._seq = 0  # transition sequence — the typed event's offset
        self.transitions = 0
        self.evaluations = 0
        self._goodput: dict[str, _Goodput] = {}
        self._deferred: dict[str, int] = {}
        # metric -> threshold_s for goodput classification (first target
        # per metric wins; lane-scoped targets classify their lane only).
        self._thresholds: dict[str, list[SLOTarget]] = {}
        for t in self.targets:
            self._thresholds.setdefault(t.metric, []).append(t)

    # --------------------------------------------------------- evaluation

    def _burn_rate(self, target: SLOTarget, hist, horizon: float) -> float:
        samples = hist.windowed_snapshot(horizon)
        if len(samples) < target.min_samples:
            return 0.0
        violating = sum(1 for s in samples if s > target.threshold_s)
        return (violating / len(samples)) / target.budget

    @staticmethod
    def _classify(target: SLOTarget, fast: float, slow: float) -> str:
        if fast >= target.shed_burn and slow >= target.burning_burn:
            return SHEDDING
        if fast >= target.burning_burn and slow >= target.warn_burn:
            return BURNING
        if fast >= target.warn_burn:
            return WARNING
        return OK

    def _scopes(self, target: SLOTarget) -> list[tuple[str, str]]:
        if target.lane is not None:
            return [("lane", target.lane)]
        scopes = [("", "")]
        for dim in ("lane", "tenant"):
            scopes.extend(
                (dim, label) for label in self.slo.labels(target.metric, dim)
            )
        return scopes

    def evaluate(self) -> dict:
        """One evaluation sweep: recompute every (target, scope) burn
        pair, walk the state machines, emit typed transition events.
        Returns ``{(metric, dim, label): state}``. Transition events are
        emitted AFTER the monitor lock is released — the tracer calls
        back into this class under its own lock (note_commit →
        note_completed), so holding ours while calling it would invert
        the lock order."""
        transitions: list[tuple] = []
        with self._lock:
            self.evaluations += 1
            for target in self.targets:
                for dim, label in self._scopes(target):
                    hist = self.slo.hist(target.metric, dim, label)
                    fast = self._burn_rate(target, hist, target.fast_window_s)
                    slow = self._burn_rate(target, hist, target.slow_window_s)
                    key = (target.metric, dim, label)
                    new = self._classify(target, fast, slow)
                    old = self._state.get(key, OK)
                    self._burn[key] = (fast, slow)
                    self._state[key] = new
                    if new != old:
                        self.transitions += 1
                        transitions.append((
                            self._seq, target.metric, dim, label,
                            old, new, fast, slow,
                        ))
                        self._seq += 1
            states = dict(self._state)
        if self.tracer is not None:
            for t in transitions:
                self.tracer.burn_state(*t)
        return states

    def state(self, metric: str, dim: str = "", label: str = "") -> str:
        with self._lock:
            return self._state.get((metric, dim, label), OK)

    def worst_state(self) -> str:
        """The highest-severity state over every monitored scope — the
        single-signal view an autoscale controller consumes (OK when no
        scope has been evaluated yet)."""
        with self._lock:
            if not self._state:
                return OK
            return max(self._state.values(), key=STATE_LEVEL.__getitem__)

    def should_defer(self, lane: str, tenant: str) -> bool:
        """The AdmissionQueue overload hook: defer this (lane, tenant)
        pop? True only for sheddable lanes (batch by default — the
        interactive lane is the SLO being protected), when the global
        scope, the lane's scope, or the tenant's scope of ANY monitored
        metric is in ``shedding``."""
        if lane not in self._shed_lanes:
            return False
        with self._lock:
            for (m, dim, label), state in self._state.items():
                if state != SHEDDING:
                    continue
                if dim == "" or (dim, label) in (
                    ("lane", lane), ("tenant", tenant),
                ):
                    return True
        return False

    # ------------------------------------------------------------ goodput

    def _classify_within(self, lane, values: dict) -> bool:
        """Did this completion meet every applicable latency target?
        ``values``: {metric: seconds-or-None}; a metric with no sample
        (e.g. TTFT on a warm resume) doesn't count against it."""
        for metric, targets in self._thresholds.items():
            v = values.get(metric)
            if v is None:
                continue
            for t in targets:
                if t.lane is not None and t.lane != lane:
                    continue
                if v > t.threshold_s:
                    return False
        return True

    def note_completed(self, lane, tenant, *, ttft_s=None, e2e_s=None,
                       itl_s=None, queue_wait_s=None) -> None:
        """One record reached COMMITTED (called by the tracer): count it
        and classify goodput against the configured thresholds."""
        within = self._classify_within(lane, {
            "ttft": ttft_s, "e2e": e2e_s, "itl": itl_s,
            "queue_wait": queue_wait_s,
        })
        with self._lock:
            g = self._goodput.setdefault(str(tenant), _Goodput())
            g.completed += 1
            if within:
                g.within_slo += 1

    def note_quarantined(self, tenant) -> None:
        with self._lock:
            g = self._goodput.setdefault(str(tenant), _Goodput())
            g.quarantined += 1

    def note_deferred(self, tenant, n: int = 1) -> None:
        """An overload deferral decision (the AdmissionQueue left this
        tenant's records queued because of the burn state)."""
        with self._lock:
            t = str(tenant)
            self._deferred[t] = self._deferred.get(t, 0) + n

    def goodput_summary(self) -> dict:
        """Per-tenant completed / within-SLO / deferred / quarantined,
        plus fleet totals — goodput is ``within_slo`` (completed work
        that met its SLO; deferred work is neither lost nor goodput)."""
        with self._lock:
            tenants = sorted(set(self._goodput) | set(self._deferred))
            per = {}
            tot_c = tot_w = tot_d = tot_q = 0
            for t in tenants:
                g = self._goodput.get(t, _Goodput())
                d = self._deferred.get(t, 0)
                per[t] = {
                    "completed": g.completed,
                    "within_slo": g.within_slo,
                    "deferred": d,
                    "quarantined": g.quarantined,
                    "goodput_ratio": (
                        round(g.within_slo / g.completed, 4)
                        if g.completed else None
                    ),
                }
                tot_c += g.completed
                tot_w += g.within_slo
                tot_d += d
                tot_q += g.quarantined
            return {
                "tenants": per,
                "completed": tot_c,
                "within_slo": tot_w,
                "deferred": tot_d,
                "quarantined": tot_q,
                "goodput_ratio": round(tot_w / tot_c, 4) if tot_c else None,
            }

    # ---------------------------------------------------------- reporting

    def summary(self) -> dict:
        with self._lock:
            states = {
                "/".join(k).strip("/"): v
                for k, v in sorted(self._state.items())
            }
            burn = {
                "/".join(k).strip("/"): {
                    "fast": round(f, 4), "slow": round(s, 4),
                }
                for k, (f, s) in sorted(self._burn.items())
            }
        out = {
            "states": states,
            "burn": burn,
            "transitions": self.transitions,
            "evaluations": self.evaluations,
            "targets": [dataclasses.asdict(t) for t in self.targets],
        }
        out["goodput"] = self.goodput_summary()
        return out

    def series(self) -> list[tuple]:
        """Exposition series for the shared renderer: numeric state +
        fast/slow burn gauges per scope, transition/evaluation counters,
        and the per-tenant goodput ledger."""
        from torchkafka_tpu.utils.metrics import format_labels

        def scope_labels(key, **extra):
            metric, dim, label = key
            lab = {"slo_metric": metric}
            if dim:
                lab[dim] = label
            lab.update(extra)
            return format_labels(**lab)

        with self._lock:
            state_entries = [
                (scope_labels(k), STATE_LEVEL[v])
                for k, v in sorted(self._state.items())
            ]
            burn_entries = []
            for k, (fast, slow) in sorted(self._burn.items()):
                burn_entries.append((scope_labels(k, window="fast"), fast))
                burn_entries.append((scope_labels(k, window="slow"), slow))
            transitions = self.transitions
            evaluations = self.evaluations
        g = self.goodput_summary()
        series: list[tuple] = [
            ("state", "gauge", state_entries or 0,
             "burn-rate state per SLO scope (0 ok / 1 warning / "
             "2 burning / 3 shedding)"),
            ("rate", "gauge", burn_entries or 0,
             "error-budget burn rate per SLO scope and window"),
            ("transitions_total", "counter", transitions,
             "burn-rate state transitions"),
            ("evaluations_total", "counter", evaluations,
             "burn-rate evaluation sweeps"),
            ("completed_total", "counter", [
                (format_labels(tenant=t), v["completed"])
                for t, v in g["tenants"].items()
            ] or 0, "completions per tenant"),
            ("completed_within_slo_total", "counter", [
                (format_labels(tenant=t), v["within_slo"])
                for t, v in g["tenants"].items()
            ] or 0, "completions that met every latency target (goodput)"),
            ("overload_deferrals_total", "counter", [
                (format_labels(tenant=t), v["deferred"])
                for t, v in g["tenants"].items()
            ] or 0, "admissions deferred by the overload hook"),
            ("quarantined_total", "counter", [
                (format_labels(tenant=t), v["quarantined"])
                for t, v in g["tenants"].items()
            ] or 0, "records dead-lettered per tenant"),
            ("goodput_ratio", "gauge", g["goodput_ratio"] or 0.0,
             "within-SLO completions / completions, fleet-wide"),
        ]
        return series

    def render_prometheus(self, prefix: str = "torchkafka_burn") -> str:
        from torchkafka_tpu.utils.metrics import render_exposition

        return render_exposition(prefix, self.series())

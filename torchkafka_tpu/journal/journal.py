"""Decode journal: the minimal resumable state of in-flight generations.

The fleet's failover story before this module was COLD: a killed
replica's uncommitted prompts redeliver and their decodes restart from
token 0 — correct (at-least-once) but wasteful, and the waste grows with
completion length. The journal records, per in-flight slot, just enough
to WARM-resume a generation on another replica (or a restarted process):

- the prompt record's identity (topic/partition/offset) plus a CRC of its
  payload (so a hint is never applied to a different record that happens
  to share an offset after topic recreation);
- the sampling contract (temperature/top_k/top_p) and the per-record RNG
  key the server derived at admit time — serve.py's per-(record, token)
  key discipline is what makes a resumed continuation token-exact;
- the tokens emitted so far (refreshed every ``cadence`` tokens, and
  always at admit and at finish).

On redelivery the resuming server prefills ``prompt + emitted_tokens`` in
ONE dispatch (a radix-cache hit when ``kv_pages`` is on, a plain longer
prefill when off) and continues decoding from the journaled position —
so the tokens re-decoded after a death are bounded by the journal cadence
instead of the whole completion, and a FINISHED-but-uncommitted entry is
served straight from the journal with zero re-decode.

Durability discipline: every flush writes the ENTIRE live-entry set
tmp → fsync → rename, so a torn write leaves the previous complete
journal visible and a partial tmp that recovery never reads
(``journal_mid_write`` in the crash matrix kills inside the tmp write to
pin exactly this). Entries for records covered by a successful offset
commit are pruned at commit flush, so the file is bounded by in-flight
work — never by history.
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from dataclasses import dataclass, field

from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source.records import Record, TopicPartition

_logger = logging.getLogger(__name__)

_VERSION = 1


def value_crc(value: bytes | None) -> int:
    return zlib.crc32(value or b"") & 0xFFFFFFFF


@dataclass
class JournalEntry:
    """One in-flight (or finished-uncommitted) generation's resumable
    state. ``tokens`` includes token 0 (the admit sample) onward; an
    admit-time entry has ``tokens == ()`` — resumable only as a cold
    admission, but its presence still proves the record was in flight."""

    topic: str
    partition: int
    offset: int
    crc: int
    key_data: tuple[int, ...] | None
    temperature: float
    top_k: int | None
    top_p: float | None
    tokens: tuple[int, ...] = ()
    finished: bool = False
    # Which model version produced the journaled tokens. A warm resume
    # only applies a hint when the server's live version matches — a
    # token prefix decoded under v0 continued under v1 would NOT be
    # byte-identical to either reference, so version-mismatched hints
    # fall back to a cold (still exactly-once) replay.
    model_version: int = 0

    @property
    def key(self) -> tuple[str, int, int]:
        return (self.topic, self.partition, self.offset)

    def to_json(self) -> dict:
        return {
            "t": self.topic,
            "p": self.partition,
            "o": self.offset,
            "crc": self.crc,
            "rng": list(self.key_data) if self.key_data is not None else None,
            "temp": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "toks": list(self.tokens),
            "fin": self.finished,
            "mv": self.model_version,
        }

    @classmethod
    def from_json(cls, d: dict) -> "JournalEntry":
        return cls(
            topic=str(d["t"]),
            partition=int(d["p"]),
            offset=int(d["o"]),
            crc=int(d["crc"]),
            key_data=(
                tuple(int(x) for x in d["rng"])
                if d.get("rng") is not None else None
            ),
            temperature=float(d["temp"]),
            top_k=None if d.get("top_k") is None else int(d["top_k"]),
            top_p=None if d.get("top_p") is None else float(d["top_p"]),
            tokens=tuple(int(x) for x in d.get("toks", ())),
            finished=bool(d.get("fin", False)),
            model_version=int(d.get("mv", 0)),
        )


@dataclass
class _Stats:
    writes: int = 0
    pruned: int = 0
    bytes_last_write: int = 0


class DecodeJournal:
    """Tmp-fsync-rename journal of live generation entries.

    ``cadence``: tokens between progress refreshes per slot (the server
    owns the counting; the journal just stores the knob so the fleet can
    construct replicas uniformly). ``fsync=False`` trades the torn-write
    guarantee for speed — benchmarks only, never correctness runs."""

    def __init__(self, path: str | os.PathLike, *, cadence: int = 8,
                 fsync: bool = True, lock: bool = True) -> None:
        if cadence < 1:
            raise ValueError(f"cadence must be >= 1 token, got {cadence}")
        self._path = os.fspath(path)
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        self.cadence = cadence
        self._fsync = fsync
        self._entries: dict[tuple[str, int, int], JournalEntry] = {}
        self._dirty = False
        self._closed = False
        # The model version this incarnation serves — journal-level meta
        # written in every flush. The swap protocol writes the NEW
        # version (durably, while the entry set is empty) BEFORE the
        # in-memory rebind, so a recovery after SIGKILL-mid-swap reads
        # load_meta() and restores exactly the weights whose outputs the
        # committed view already attributes to this member.
        self.model_version = 0
        self.stats = _Stats()
        # Single-writer discipline across PROCESSES: a journal file is one
        # replica incarnation's private state; two live writers would
        # interleave tmp-renames and hand survivors a chimera. The lock
        # file carries the owner pid — a dead owner's lock (SIGKILL never
        # cleans up) or our own is stale and silently stolen.
        self._lock_held = False
        if lock:
            self._acquire_lock()

    def _acquire_lock(self) -> None:
        from torchkafka_tpu.errors import JournalLockedError

        lock_path = self._path + ".lock"
        my_pid = os.getpid()
        for _ in range(2):
            try:
                fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                with os.fdopen(fd, "w") as f:
                    f.write(str(my_pid))
                self._lock_held = True
                return
            except FileExistsError:
                try:
                    with open(lock_path) as f:
                        owner = int(f.read().strip() or "0")
                except (OSError, ValueError):
                    owner = 0
                stale = owner == 0 or owner == my_pid
                if not stale:
                    try:
                        os.kill(owner, 0)  # signal 0: existence probe only
                    except ProcessLookupError:
                        stale = True
                    except PermissionError:
                        pass  # alive, different uid: definitely not ours
                if not stale:
                    raise JournalLockedError(
                        f"decode journal {self._path!r} is owned by live "
                        f"process {owner}; journals are single-writer — "
                        "give each replica incarnation its own path"
                    )
                try:
                    os.unlink(lock_path)
                except FileNotFoundError:
                    pass
        raise JournalLockedError(
            f"could not acquire journal lock {lock_path!r} (contended)"
        )

    def _release_lock(self) -> None:
        if not self._lock_held:
            return
        self._lock_held = False
        try:
            os.unlink(self._path + ".lock")
        except OSError:
            pass

    @property
    def path(self) -> str:
        return self._path

    # ------------------------------------------------------------- recording

    def record(
        self,
        record: Record,
        key_data,
        *,
        tokens=(),
        finished: bool = False,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        model_version: int = 0,
    ) -> None:
        """Upsert the entry for ``record`` (admit / progress / adoption
        after a warm resume). Marks the journal dirty; the caller flushes
        at its cadence points."""
        entry = JournalEntry(
            topic=record.topic,
            partition=record.partition,
            offset=record.offset,
            crc=value_crc(record.value),
            key_data=(
                tuple(int(x) for x in key_data)
                if key_data is not None else None
            ),
            temperature=float(temperature),
            top_k=top_k,
            top_p=top_p,
            tokens=tuple(int(t) for t in tokens),
            finished=finished,
            model_version=int(model_version),
        )
        self._entries[entry.key] = entry
        self._dirty = True

    def progress(self, record: Record, tokens) -> None:
        """Refresh an existing entry's emitted tokens (cadence append)."""
        key = (record.topic, record.partition, record.offset)
        entry = self._entries.get(key)
        if entry is None:
            return  # admitted before the journal was attached: nothing to do
        entry.tokens = tuple(int(t) for t in tokens)
        self._dirty = True

    def finish(self, record: Record, tokens) -> None:
        """Mark the record's generation complete with its final tokens —
        always journaled, so a finished-but-uncommitted completion can be
        re-served from the journal with zero re-decode."""
        key = (record.topic, record.partition, record.offset)
        entry = self._entries.get(key)
        if entry is None:
            return
        entry.tokens = tuple(int(t) for t in tokens)
        entry.finished = True
        self._dirty = True

    def prune(self, watermarks: dict[TopicPartition, int]) -> int:
        """Drop entries covered by a successful commit: every entry whose
        offset sits below its partition's committed next-read offset is
        durable history, not in-flight work. Called at commit flush —
        this is what bounds the file by live work (marks dirty only if
        something was actually dropped)."""
        wm = {(tp.topic, tp.partition): off for tp, off in watermarks.items()}
        drop = [
            k for k, e in self._entries.items()
            if e.offset < wm.get((e.topic, e.partition), -1)
        ]
        for k in drop:
            del self._entries[k]
        if drop:
            self._dirty = True
            self.stats.pruned += len(drop)
        return len(drop)

    def set_model_version(self, version: int) -> None:
        """Record the serving model version as journal-level meta. The
        swap protocol calls this (then ``sync()``) while the entry set is
        empty and the commit window is closed — the durable version flip
        IS the swap's commit point: recovery before it restarts on the
        old weights, recovery after it restarts on the new."""
        version = int(version)
        if version != self.model_version:
            self.model_version = version
            self._dirty = True

    # ----------------------------------------------------------- persistence

    def flush(self) -> None:
        """Write the live-entry set if anything changed: full payload to
        ``<path>.tmp``, fsync, atomic rename. A death anywhere inside
        leaves the PREVIOUS journal intact (the crash matrix kills at
        ``journal_mid_write`` to prove it)."""
        if not self._dirty:
            return
        payload = json.dumps({
            "version": _VERSION,
            "cadence": self.cadence,
            "model_version": self.model_version,
            "entries": [e.to_json() for e in self._entries.values()],
        }).encode()
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            # Two-part write around the crash hook: a kill here leaves a
            # torn tmp on disk — exactly the artifact recovery must never
            # read (load() only ever opens the renamed path).
            half = len(payload) // 2
            f.write(payload[:half])
            crash_hook("journal_mid_write")
            f.write(payload[half:])
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._path)
        self._dirty = False
        self.stats.writes += 1
        self.stats.bytes_last_write = len(payload)

    def sync(self) -> None:
        """Unconditional durability point (the SIGTERM drain path): flush
        pending state even if the dirty flag is unset-but-stale-on-disk
        is impossible by construction, so this is flush() plus tolerance
        for being called on a closed journal."""
        if self._closed:
            return
        self.flush()

    def close(self) -> None:
        """Idempotent: the drain path may hit this twice (second signal)."""
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._closed = True
            self._release_lock()

    # -------------------------------------------------------------- querying

    def live_entries(self) -> dict[tuple[str, int, int], JournalEntry]:
        """The IN-MEMORY entry set (may be ahead of disk by < cadence
        tokens). Failover consults ``load()`` — the disk truth a crash
        leaves behind — not this."""
        return dict(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def scan_dir(
        journal_dir: str | os.PathLike,
        exclude: tuple[str, ...] = (),
    ) -> dict[tuple[str, int, int], JournalEntry]:
        """Cross-process journal discovery: load EVERY journal file in
        ``journal_dir`` except the caller's own (``exclude`` paths) and
        merge their live entries — what a survivor (or a freshly spawned
        replacement) consults after a peer's death, and what a restarting
        fleet consults for every previous incarnation at once. Entries
        for the same record across files keep the FRESHEST copy
        (finished beats in-flight, more emitted tokens beat fewer) — a
        record that migrated between incarnations leaves a stale shadow
        in the older file. Deterministic: files visited in sorted order,
        and hints are CRC-gated at apply time, so a stale or foreign
        entry can never corrupt a resume. The ``journal_handoff_pre_load``
        crash point pins the window where a loader dies mid-scan: the
        files are read-only here, so the next scan sees identical state."""
        crash_hook("journal_handoff_pre_load")
        journal_dir = os.fspath(journal_dir)
        excluded = {os.path.abspath(os.fspath(p)) for p in exclude}
        merged: dict[tuple[str, int, int], JournalEntry] = {}
        try:
            names = sorted(os.listdir(journal_dir))
        except FileNotFoundError:
            return {}
        for name in names:
            if not name.endswith(".json"):
                continue  # .tmp (torn writes) and .lock files are not journals
            path = os.path.join(journal_dir, name)
            if os.path.abspath(path) in excluded:
                continue
            for key, entry in DecodeJournal.load(path).items():
                old = merged.get(key)
                if old is None or (
                    (entry.finished, len(entry.tokens))
                    > (old.finished, len(old.tokens))
                ):
                    merged[key] = entry
        return merged

    @staticmethod
    def load_meta(path: str | os.PathLike) -> dict:
        """Read a journal file's top-level metadata (notably
        ``model_version``) without materializing entries — what a
        restarting incarnation consults FIRST, so it rebuilds the weights
        its previous life durably committed to before touching any hint.
        Missing or corrupt file → ``{}`` (boot on the spec's version)."""
        path = os.fspath(path)
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode())
            if not isinstance(doc, dict):
                return {}
        except FileNotFoundError:
            return {}
        except (OSError, ValueError) as exc:
            _logger.warning(
                "ignoring unreadable decode journal meta %s (%s)", path, exc,
            )
            return {}
        return {k: v for k, v in doc.items() if k != "entries"}

    @staticmethod
    def load(path: str | os.PathLike) -> dict[tuple[str, int, int], JournalEntry]:
        """Read a journal file as a dead process's survivors see it.
        Missing file → no entries (the replica never journaled); a
        corrupt file warns and yields nothing (fail to cold replay,
        never crash recovery) — though corruption is unreachable through
        this module's own writes (rename is atomic)."""
        path = os.fspath(path)
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode())
            entries = [JournalEntry.from_json(d) for d in doc["entries"]]
        except FileNotFoundError:
            return {}
        except (OSError, ValueError, KeyError, TypeError) as exc:
            _logger.warning(
                "ignoring unreadable decode journal %s (%s); affected "
                "prompts will cold-replay", path, exc,
            )
            return {}
        return {e.key: e for e in entries}

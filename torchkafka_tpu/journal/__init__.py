"""Decode journal: per-replica resumable generation state for warm
failover (see journal.py's module docstring for the full design)."""

from torchkafka_tpu.journal.journal import (
    DecodeJournal,
    JournalEntry,
    value_crc,
)

__all__ = ["DecodeJournal", "JournalEntry", "value_crc"]

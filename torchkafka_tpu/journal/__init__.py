"""Decode journal: per-replica resumable generation state for warm
failover (see journal.py's module docstring for the full design).
``DecodeJournal.scan_dir`` is the cross-process discovery path: a
survivor of a peer's death (or a freshly spawned replacement) merges
every journal file in the shared directory into warm-resume hints."""

from torchkafka_tpu.journal.journal import (
    DecodeJournal,
    JournalEntry,
    value_crc,
)

__all__ = ["DecodeJournal", "JournalEntry", "value_crc"]

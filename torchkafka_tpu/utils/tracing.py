"""Profiler hooks: XLA traces and named spans around the ingest loop.

The reference has no instrumentation at all (SURVEY.md §5 tracing row). On
TPU the tool that matters is the XLA profiler — these helpers wire the
ingest loop into it so a trace shows host poll/decode time, transfer, the
step, and the commit barrier as separate named spans on the timeline.

    with tracing.trace_session("/tmp/trace"):
        for i, (batch, token) in enumerate(stream):
            with tracing.step_span(i):
                loss = train_step(batch.data)
                token.commit(wait_for=loss)
    # then: xprof / tensorboard --logdir /tmp/trace
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace_session(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace for the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_span(step: int):
    """Annotate one training/inference step on the trace timeline."""
    return jax.profiler.StepTraceAnnotation("tk_step", step_num=step)


def span(name: str):
    """Annotate an arbitrary host-side region (e.g. 'decode', 'commit')."""
    return jax.profiler.TraceAnnotation(name)


def ingest_lag_ms(record_timestamp_ms: int, now_ms: float | None = None) -> float:
    """End-to-end lag: record append time -> now. The streaming SLO metric
    (how far behind the head of the topic the consumer is running)."""
    import time

    if now_ms is None:
        now_ms = time.time() * 1e3
    return max(0.0, now_ms - record_timestamp_ms) if record_timestamp_ms else 0.0

"""Profiler hooks: XLA traces and named spans for ingest AND serving.

The reference has no instrumentation at all (SURVEY.md §5 tracing row). On
TPU the tool that matters is the XLA profiler — these helpers wire the
host loops into it so a trace shows the named host stages on the timeline.

Training ingest:

    with tracing.trace_session("/tmp/trace"):
        for i, (batch, token) in enumerate(stream):
            with tracing.step_span(i):
                loss = train_step(batch.data)
                token.commit(wait_for=loss)
    # then: xprof / tensorboard --logdir /tmp/trace

Serving: ``serve.py`` threads ``span``s through its own hot path — wrap
the run in ``trace_session`` and the timeline shows the serving stages as
named host regions around the device programs:

    tk_serve:admit        prefill-admission dispatch (dense / legacy paged)
    tk_serve:chunk_pack   host packing of the fused tick's prefill chunk
    tk_serve:tick         the decode (or fused chunk) tick dispatch
    tk_serve:sync         the once-per-tick-block host sync (device_get)
    tk_serve:commit       output flush + durability waits + offset commit

Record-level lifecycle tracing (who waited where, per record) is the
separate ``torchkafka_tpu.obs`` subsystem; these annotations are the
profiler-timeline complement.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator

import jax

# Serving span names (one place, so the README recipe and serve.py agree).
SPAN_ADMIT = "tk_serve:admit"
SPAN_CHUNK_PACK = "tk_serve:chunk_pack"
SPAN_TICK = "tk_serve:tick"
SPAN_SYNC = "tk_serve:sync"
SPAN_COMMIT = "tk_serve:commit"


@contextlib.contextmanager
def trace_session(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace for the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_span(step: int):
    """Annotate one training/inference step on the trace timeline."""
    return jax.profiler.StepTraceAnnotation("tk_step", step_num=step)


def span(name: str):
    """Annotate an arbitrary host-side region (e.g. 'decode', 'commit')."""
    return jax.profiler.TraceAnnotation(name)


def ingest_lag_ms(
    record_timestamp_ms: int,
    now_ms: float | None = None,
    clock: Callable[[], float] | None = None,
) -> float:
    """End-to-end lag: record append time -> now. The streaming SLO metric
    (how far behind the head of the topic the consumer is running).

    ``clock`` returns SECONDS on the same timeline record timestamps are
    stamped from (epoch seconds for real brokers) — inject a
    ``resilience.ManualClock.now`` and lag becomes exactly testable
    instead of wall-clock-dependent; ``now_ms`` overrides both (legacy
    spelling, kept for callers that already hold a reading)."""
    if now_ms is None:
        now_ms = (clock() if clock is not None else time.time()) * 1e3
    return max(0.0, now_ms - record_timestamp_ms) if record_timestamp_ms else 0.0

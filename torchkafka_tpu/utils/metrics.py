"""Ingest observability: rate + latency metrics.

The reference's only observability is a module logger (SURVEY.md §5 metrics
row: debug/info on commit, error on failure). We keep equivalent log points
(in commit/token.py) and add the counters BASELINE.md measures: records/sec
sustained and offset-commit latency percentiles.
"""

from __future__ import annotations

import threading
import time


class RateMeter:
    """Counts events; reports average rate over its lifetime and windows."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._count = 0
            self._t0 = time.perf_counter()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def rate(self) -> float:
        with self._lock:
            dt = time.perf_counter() - self._t0
            return self._count / dt if dt > 0 else 0.0


class LatencyHistogram:
    """Latency percentiles over a bounded window of recent samples.

    Bounded (ring buffer) because streams may run forever
    (idle_timeout_ms=None); recent-window percentiles are also what an
    operator actually wants from a long-lived pipeline.

    ``window_s`` (with an injectable ``clock``) adds a TIME-windowed view
    on top of the cumulative one: samples also land in a bounded ring of
    per-window delta buckets (bucket width ``window_s``, ``n_windows``
    retained), so ``windowed_summary(seconds)`` reports percentiles "over
    the last N seconds" — the signal a burn-rate monitor needs, which the
    cumulative window cannot provide (it never forgets). Window roll is
    clock-driven and bucket-granular: a horizon of S seconds covers the
    current (partial) bucket plus ``ceil(S / window_s) `` completed ones,
    exact under a ManualClock. None (default) keeps the class byte-for-
    byte on its original cumulative-only behavior and cost."""

    def __init__(self, window: int = 8192, *, window_s: float | None = None,
                 n_windows: int = 16, clock=None) -> None:
        from collections import deque

        self._lock = threading.Lock()
        self._samples: "deque[float]" = deque(maxlen=window)
        self._total = 0
        if window_s is not None and window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if n_windows < 1:
            raise ValueError(f"n_windows must be >= 1, got {n_windows}")
        self._window_s = window_s
        self._clock = clock or time.monotonic
        # (bucket_index, [samples]) newest last; bounded by n_windows.
        self._buckets: "deque[tuple[int, list[float]]]" = deque(
            maxlen=n_windows
        )

    def _bucket(self, now: float) -> list:
        """The current window's sample list (lock held)."""
        idx = int(now // self._window_s)
        if not self._buckets or self._buckets[-1][0] != idx:
            self._buckets.append((idx, []))
        return self._buckets[-1][1]

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self._total += 1
            if self._window_s is not None:
                self._bucket(self._clock()).append(seconds)

    def observe_many(self, seconds: float, n: int) -> None:
        """``n`` identical samples under one lock acquisition (the SLO
        inter-token path observes per token at host-sync granularity)."""
        with self._lock:
            self._samples.extend([seconds] * n)
            self._total += n
            if self._window_s is not None:
                self._bucket(self._clock()).extend([seconds] * n)

    def windowed_snapshot(self, seconds: float | None = None) -> list[float]:
        """Samples observed within the last ``seconds`` (default: one
        ``window_s``), at bucket granularity: the current partial bucket
        plus every completed bucket whose window intersects
        ``(now - seconds, now]``. Raises unless time-windowing is on."""
        if self._window_s is None:
            raise ValueError(
                "time-windowed view requires LatencyHistogram(window_s=...)"
            )
        horizon = self._window_s if seconds is None else float(seconds)
        with self._lock:
            now = self._clock()
            # A bucket [idx*w, (idx+1)*w) intersects (now-horizon, now]
            # iff its END is past the horizon start.
            min_idx = int((now - horizon) // self._window_s)
            out: list[float] = []
            for idx, samples in self._buckets:
                if idx >= min_idx:
                    out.extend(samples)
            return out

    def windowed_summary(self, seconds: float | None = None) -> dict:
        """count/p50_ms/p99_ms over the last ``seconds`` (see
        ``windowed_snapshot`` for the bucket-granular roll contract)."""
        samples = self.windowed_snapshot(seconds)
        if not samples:
            return {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0}
        s = sorted(samples)

        def pct(q: float) -> float:
            idx = min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))
            return s[idx] * 1e3

        return {"count": len(s), "p50_ms": pct(50), "p99_ms": pct(99)}

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
            idx = min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))
            return s[idx]

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self) -> list[float]:
        """Copy of the retained sample window (seconds) — what fleet-level
        aggregation pools across replicas before taking percentiles."""
        with self._lock:
            return list(self._samples)

    def summary(self) -> dict[str, float]:
        return {
            "count": self.count,
            "p50_ms": self.percentile(50) * 1e3,
            "p99_ms": self.percentile(99) * 1e3,
        }


def merge_latency_summaries(histograms: "list[LatencyHistogram]") -> dict:
    """Pool several histograms' retained samples into one percentile
    summary (same shape as ``LatencyHistogram.summary``). Percentiles of
    the pooled window, not averages of per-histogram percentiles — a
    replica with 10× the commits weighs 10× the samples."""
    samples: list[float] = []
    total = 0
    for h in histograms:
        samples.extend(h.snapshot())
        total += h.count
    if not samples:
        return {"count": total, "p50_ms": 0.0, "p99_ms": 0.0}
    s = sorted(samples)

    def pct(q: float) -> float:
        idx = min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))
        return s[idx] * 1e3

    return {"count": total, "p50_ms": pct(50), "p99_ms": pct(99)}


class Gauge:
    """Last-observed value (e.g. ingest lag)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def escape_label_value(value) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote, and newline must be escaped or the sample line is unparsable.
    Label VALUES may otherwise be any UTF-8 — tenant names come straight
    from record keys, so this is load-bearing, not cosmetic."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(**labels) -> str:
    """Render a label set for ``render_exposition`` entries with values
    escaped: ``format_labels(tenant='a"b', percentile="p50")`` →
    ``tenant="a\\"b",percentile="p50"``. Insertion-ordered (callers pick
    the display order); None values are skipped."""
    return ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in labels.items() if v is not None
    )


def render_exposition(prefix: str, series: list[tuple]) -> str:
    """Prometheus text exposition shared by every metrics set. ``series``:
    (name, type, value) or (name, type, value, help) — value a number, or
    a list of (labels, number) where labels is a pre-rendered label body
    (build dynamic ones with ``format_labels`` so values are escaped).
    Every metric gets a ``# HELP`` and ``# TYPE`` line (help defaults to
    the name with underscores spaced — enough for the conformance
    contract; pass real help text where it adds signal). Counters follow
    the _total convention at the call site; gauges format with :.6g."""
    lines = []
    for entry in series:
        name, mtype, value = entry[:3]
        help_text = entry[3] if len(entry) > 3 else name.replace("_", " ")
        full = f"{prefix}_{name}"
        lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} {mtype}")
        entries = value if isinstance(value, list) else [("", value)]
        for labels, v in entries:
            label_part = f"{{{labels}}}" if labels else ""
            v_part = f"{v:.6g}" if mtype == "gauge" else f"{v}"
            lines.append(f"{full}{label_part} {v_part}")
    return "\n".join(lines) + "\n"


class ResilienceMetrics:
    """The metric set one ResilientConsumer maintains (resilience/).

    Counters follow the layer's three escalation stages: a *retry* is a
    fault absorbed inside one operation; a *degraded poll* is an
    operation that gave up for this round (empty result, watermark
    intact); a *suppressed* operation never reached the transport at all
    because the circuit was open. ``circuit_state`` is the breaker gauge
    (0 closed / 0.5 half-open / 1 open); ``circuit_opens``/``closes``
    mirror the breaker's transition counters so "opened then closed" is
    assertable from a metrics snapshot alone."""

    def __init__(self) -> None:
        self.retries = RateMeter()  # backoff-scheduled retry attempts
        self.poll_faults = RateMeter()  # retryable poll failures observed
        self.commit_faults = RateMeter()  # retryable commit failures observed
        self.degraded_polls = RateMeter()  # polls that gave up -> []
        self.suppressed_polls = RateMeter()  # fast-failed: circuit open
        self.suppressed_commits = RateMeter()  # fast-failed: circuit open
        self.circuit_opens = RateMeter()
        self.circuit_closes = RateMeter()
        self.circuit_state = Gauge()

    def summary(self) -> dict:
        return {
            "retries": self.retries.count,
            "poll_faults": self.poll_faults.count,
            "commit_faults": self.commit_faults.count,
            "degraded_polls": self.degraded_polls.count,
            "suppressed_polls": self.suppressed_polls.count,
            "suppressed_commits": self.suppressed_commits.count,
            "circuit_opens": self.circuit_opens.count,
            "circuit_closes": self.circuit_closes.count,
            "circuit_state": self.circuit_state.value,
        }

    def render_prometheus(self, prefix: str = "torchkafka_resilience") -> str:
        s = self.summary()
        return render_exposition(prefix, [
            ("retries_total", "counter", s["retries"]),
            ("poll_faults_total", "counter", s["poll_faults"]),
            ("commit_faults_total", "counter", s["commit_faults"]),
            ("degraded_polls_total", "counter", s["degraded_polls"]),
            ("suppressed_polls_total", "counter", s["suppressed_polls"]),
            ("suppressed_commits_total", "counter", s["suppressed_commits"]),
            ("circuit_opens_total", "counter", s["circuit_opens"]),
            ("circuit_closes_total", "counter", s["circuit_closes"]),
            ("circuit_state", "gauge", s["circuit_state"]),
        ])


class StreamMetrics:
    """The metric set one KafkaStream maintains."""

    def __init__(self) -> None:
        self.records = RateMeter()  # records fetched off the broker
        self.batches = RateMeter()  # batches emitted to the consumer
        self.dropped = RateMeter()  # records dropped by the processor
        self.processor_errors = RateMeter()  # drops caused by a RAISING processor
        self.quarantined = RateMeter()  # poison records dead-lettered (resolved)
        self.dlq_delivery_failures = RateMeter()  # DLQ produces that FAILED —
        # the record is lost to the quarantine topic (the stream's guard
        # swallows the exception by contract; this counter is the page)
        self.commit_latency = LatencyHistogram()
        self.commit_failures = RateMeter()
        self.ingest_lag_ms = Gauge()  # append-time -> poll-time of newest record

    def summary(self) -> dict:
        return {
            "records": self.records.count,
            "records_per_s": self.records.rate(),
            "batches": self.batches.count,
            "dropped": self.dropped.count,
            "processor_errors": self.processor_errors.count,
            "quarantined": self.quarantined.count,
            "dlq_delivery_failures": self.dlq_delivery_failures.count,
            "commit": self.commit_latency.summary(),
            "commit_failures": self.commit_failures.count,
            "ingest_lag_ms": round(self.ingest_lag_ms.value, 3),
        }

    def render_prometheus(self, prefix: str = "torchkafka") -> str:
        """Prometheus text exposition of the summary — paste into any
        scrape endpoint. Names follow the counter/gauge conventions
        (_total suffix on monotone counters, unit-suffixed gauges); the
        latency percentiles use a 'percentile' label, not 'quantile',
        which the exposition format reserves for TYPE summary series."""
        s = self.summary()
        return render_exposition(prefix, [
            ("records_total", "counter", s["records"]),
            ("batches_total", "counter", s["batches"]),
            ("dropped_records_total", "counter", s["dropped"]),
            ("processor_errors_total", "counter", s["processor_errors"]),
            ("quarantined_records_total", "counter", s["quarantined"]),
            ("dlq_delivery_failures_total", "counter", s["dlq_delivery_failures"]),
            ("commit_failures_total", "counter", s["commit_failures"]),
            ("commits_total", "counter", s["commit"]["count"]),
            ("records_per_second", "gauge", s["records_per_s"]),
            ("commit_latency_ms", "gauge", [
                ('percentile="p50"', s["commit"]["p50_ms"]),
                ('percentile="p99"', s["commit"]["p99_ms"]),
            ]),
            ("ingest_lag_ms", "gauge", s["ingest_lag_ms"]),
        ])


class BrokerMetrics:
    """The metric set a durable ``InMemoryBroker`` maintains: WAL write
    cost (appends, bytes, fsyncs), recovery outcome (events/records
    replayed, dangling transactions aborted, torn-tail bytes truncated,
    wall-clock to recover), and — when the broker leads a replicated
    cell — the replication plane (frames shipped/applied, quorum
    commits, stale-epoch rejections, elections won) — the operator's
    answer to "what did that broker restart cost and what did it
    salvage". Rendered on the same shared exposition grammar as every
    other metrics class so the fleet endpoint serves it from the same
    scrape."""

    def __init__(self) -> None:
        self.wal_appends = RateMeter()
        self.wal_bytes_written = RateMeter()
        self.wal_fsyncs = RateMeter()
        self.recoveries = RateMeter()
        self.recovery_replayed_events = RateMeter()
        self.recovery_replayed_records = RateMeter()
        self.recovery_aborted_txns = RateMeter()
        self.recovery_truncated_bytes = RateMeter()
        self.recovery_ms = Gauge()  # last recovery's replay wall-clock
        # Replication plane (zero for a bare, cell-less broker).
        self.repl_frames_shipped = RateMeter()
        self.repl_frames_applied = RateMeter()
        self.repl_quorum_commits = RateMeter()
        self.repl_stale_rejections = RateMeter()
        self.elections = RateMeter()

    def summary(self) -> dict:
        return {
            "wal_appends": self.wal_appends.count,
            "wal_bytes_written": self.wal_bytes_written.count,
            "wal_fsyncs": self.wal_fsyncs.count,
            "recoveries": self.recoveries.count,
            "recovery_replayed_events": self.recovery_replayed_events.count,
            "recovery_replayed_records": self.recovery_replayed_records.count,
            "recovery_aborted_txns": self.recovery_aborted_txns.count,
            "recovery_truncated_bytes": self.recovery_truncated_bytes.count,
            "recovery_ms": round(self.recovery_ms.value, 3),
            "repl_frames_shipped": self.repl_frames_shipped.count,
            "repl_frames_applied": self.repl_frames_applied.count,
            "repl_quorum_commits": self.repl_quorum_commits.count,
            "repl_stale_rejections": self.repl_stale_rejections.count,
            "elections": self.elections.count,
        }

    def render_prometheus(self, prefix: str = "torchkafka_broker") -> str:
        s = self.summary()
        return render_exposition(prefix, [
            ("wal_appends_total", "counter", s["wal_appends"]),
            ("wal_bytes_written_total", "counter", s["wal_bytes_written"]),
            ("wal_fsyncs_total", "counter", s["wal_fsyncs"]),
            ("recoveries_total", "counter", s["recoveries"]),
            ("recovery_replayed_events_total", "counter",
             s["recovery_replayed_events"]),
            ("recovery_replayed_records_total", "counter",
             s["recovery_replayed_records"]),
            ("recovery_aborted_txns_total", "counter",
             s["recovery_aborted_txns"]),
            ("recovery_truncated_bytes_total", "counter",
             s["recovery_truncated_bytes"]),
            ("recovery_ms", "gauge", s["recovery_ms"]),
            ("repl_frames_shipped_total", "counter",
             s["repl_frames_shipped"]),
            ("repl_frames_applied_total", "counter",
             s["repl_frames_applied"]),
            ("repl_quorum_commits_total", "counter",
             s["repl_quorum_commits"]),
            ("repl_stale_rejections_total", "counter",
             s["repl_stale_rejections"]),
            ("elections_total", "counter", s["elections"]),
        ])

from torchkafka_tpu.utils.devices import force_cpu_devices
from torchkafka_tpu.utils.metrics import LatencyHistogram, RateMeter, StreamMetrics
from torchkafka_tpu.utils.shutdown import ShutdownSignal

__all__ = [
    "LatencyHistogram",
    "RateMeter",
    "ShutdownSignal",
    "StreamMetrics",
    "force_cpu_devices",
]

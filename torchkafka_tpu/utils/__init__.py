from torchkafka_tpu.utils.metrics import LatencyHistogram, RateMeter, StreamMetrics

__all__ = ["LatencyHistogram", "RateMeter", "StreamMetrics"]

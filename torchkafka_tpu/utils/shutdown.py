"""Cooperative preemption drain: SIGTERM → finish the step, commit, exit.

The zero-loss story on host preemption never depended on this module: an
uncommitted batch simply re-delivers (the reference's core contract,
/root/reference/src/kafka_dataset.py:89 — never commit on teardown). What
a hard kill costs is DUPLICATE work: everything since the last commit
replays. TPU preemption notices (maintenance events, spot reclaims) arrive
as SIGTERM with a grace window, so a training loop that drains
cooperatively — finish the in-flight step, commit its offsets, checkpoint
— resumes with zero replay instead of a commit-cadence's worth.

Usage::

    with ShutdownSignal() as stop:
        for batch, token in stream:
            ...step...
            token.commit(wait_for=loss)
            if stop.requested:          # SIGTERM arrived mid-step
                ckpt.save(step, state, token.offsets)
                break                   # clean exit; nothing replays

The handler only sets a flag — all draining happens at the loop's own
safe point, the same deferred-commit discipline the reference used for
its worker signals (/root/reference/src/kafka_dataset.py:93-118, where
the handler also only sets ``_commit_required``). A SECOND signal while
draining re-raises the default behavior (so a stuck drain can still be
killed, and the at-least-once contract covers the replay).
"""

from __future__ import annotations

import logging
import signal as _signal
import threading
from types import FrameType

logger = logging.getLogger(__name__)


class ShutdownSignal:
    """Context manager installing set-a-flag handlers for ``signals``.

    Main-thread only (CPython restricts ``signal.signal`` to the main
    thread); entering from another thread raises. Re-entrant installs are
    rejected — nesting would silently drop the outer drain."""

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT)) -> None:
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._saved: dict[int, object] = {}
        self._received: int | None = None

    @property
    def requested(self) -> bool:
        """True once any registered signal has arrived."""
        return self._event.is_set()

    @property
    def received_signal(self) -> int | None:
        return self._received

    def _handle(self, signum: int, frame: FrameType | None) -> None:
        if self._event.is_set():
            # Second signal while draining: restore default and re-raise
            # so a wedged drain is still killable. Nothing was committed
            # for unfinished work, so the replay contract covers it.
            logger.warning(
                "second signal %d during drain; restoring default handler",
                signum,
            )
            # UNCONDITIONALLY the default action — restoring a saved
            # SIG_IGN (background jobs inherit SIGINT=SIG_IGN) would make
            # the re-raise a no-op and the "kill a stuck drain" promise
            # silently fail. __exit__ still restores the saved handler on
            # the normal path.
            _signal.signal(signum, _signal.SIG_DFL)
            _signal.raise_signal(signum)
            return
        self._received = signum
        self._event.set()
        logger.info(
            "signal %d received; draining at the next loop safe point "
            "(commit-then-exit — a second signal kills immediately)",
            signum,
        )

    def __enter__(self) -> "ShutdownSignal":
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError("ShutdownSignal must be entered on the main thread")
        if self._saved:
            raise RuntimeError("ShutdownSignal is not re-entrant")
        # Fresh state per with-block: a reused instance must not report a
        # PREVIOUS run's signal as an immediate drain request.
        self._event.clear()
        self._received = None
        try:
            for s in self._signals:
                self._saved[s] = _signal.getsignal(s)
                _signal.signal(s, self._handle)
        except BaseException:
            # Partial install (an invalid signal later in the tuple) must
            # not leak handlers pointing at an orphaned instance — roll
            # back what was installed, leave the instance reusable.
            self.__exit__()
            raise
        return self

    def __exit__(self, *exc) -> None:
        for s, old in self._saved.items():
            # None = handler installed by non-Python code; SIG_DFL is the
            # closest restorable behavior (signal.signal rejects None).
            _signal.signal(s, old or _signal.SIG_DFL)  # type: ignore[arg-type]
        self._saved.clear()

"""Two-point slope timing for high-latency dispatch transports.

Any timing of the form "run K device iterations, fetch, divide by K"
carries the constant dispatch+fetch round trip in every estimate — ~90 ms
through the dev tunnel, i.e. ~12 ms/iter of pure overhead at K=8, enough
to bury the 4.7 ms quantity being measured (measured round 4, ResNet-50).
Timing TWO chain lengths and taking the slope cancels the constant term
exactly. One implementation, shared by serve.decode_roofline and the
harness scenarios.
"""

from __future__ import annotations


def two_point_slope(
    t_short: float, t_long: float, k_short: int, k_long: int
) -> tuple[float, float, bool]:
    """(per_iteration_s, overhead_s, ok).

    ``ok`` is False when the slope degenerates (t_long <= t_short): the
    transport drifted between the two windows by more than the device work
    separating them, and nothing numeric can honestly be derived — callers
    must FLAG the measurement, not publish the floored values (a 1e-9
    floor silently becomes "1.6e10 tok/s" downstream). The floored
    per-iteration value is still returned so callers can avoid division
    by zero while reporting the failure.
    """
    if k_long <= k_short:
        raise ValueError("k_long must exceed k_short")
    slope = (t_long - t_short) / (k_long - k_short)
    ok = slope > 0
    per_iter = max(slope, 1e-9)
    overhead = max(t_short - k_short * per_iter, 0.0)
    return per_iter, overhead, ok

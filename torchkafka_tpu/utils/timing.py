"""Two-point slope timing for high-latency dispatch transports.

Any timing of the form "run K device iterations, fetch, divide by K"
carries the constant dispatch+fetch round trip in every estimate — ~90 ms
through the dev tunnel, i.e. ~12 ms/iter of pure overhead at K=8, enough
to bury the 4.7 ms quantity being measured (measured round 4, ResNet-50).
Timing TWO chain lengths and taking the slope cancels the constant term
exactly. One implementation, shared by serve.decode_roofline and the
harness scenarios.
"""

from __future__ import annotations


def device_step_seconds(
    step_fn, params, opt_state, *batch_args,
    k_short: int = 2, k_long: int = 8, repeats: int = 3,
) -> tuple[float, bool]:
    """Pure DEVICE seconds per train step: (step_s, ok).

    Chains the step INSIDE one jitted ``lax.fori_loop`` (so the host
    dispatches once per window, not once per step) and slopes two loop
    lengths. This matters on RPC-dispatch transports where each dispatch
    costs ~10 ms of host work: a Python-loop chain of jitted calls there
    measures the host's dispatch rate, not the device — wall/step keeps
    FALLING as the window grows and never converges to the device time.

    ``step_fn(params, opt, *batch_args) -> (params, opt, loss)`` (the
    make_train_step / make_dlrm_train_step shape; donation inside the
    outer jit is inert, which is fine — buffer reuse across loop
    iterations is XLA's job here).
    """
    import time

    import jax
    import numpy as np
    from jax import lax

    # k is a TRACED loop bound (one compile serves both window lengths —
    # a static bound would compile the full step loop twice, minutes each
    # on remote-compile transports).
    @jax.jit
    def run(k, p, o, *args):
        def body(_, carry):
            p, o = carry
            p, o, _loss = step_fn(p, o, *args)
            return (p, o)

        p, o = lax.fori_loop(0, k, body, (p, o))
        # Scalar fence transitively dependent on every iteration.
        return jax.tree_util.tree_leaves(p)[0].ravel()[0]

    float(run(k_short, params, opt_state, *batch_args))  # compile + warm
    shorts, longs = [], []
    for _ in range(repeats):  # interleaved: drift can't flip the slope
        t0 = time.perf_counter()
        float(run(k_short, params, opt_state, *batch_args))
        shorts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        float(run(k_long, params, opt_state, *batch_args))
        longs.append(time.perf_counter() - t0)
    step_s, _overhead, ok = two_point_slope(
        float(np.median(shorts)), float(np.median(longs)), k_short, k_long
    )
    return step_s, ok


def two_point_slope(
    t_short: float, t_long: float, k_short: int, k_long: int
) -> tuple[float, float, bool]:
    """(per_iteration_s, overhead_s, ok).

    ``ok`` is False when the slope degenerates (t_long <= t_short): the
    transport drifted between the two windows by more than the device work
    separating them, and nothing numeric can honestly be derived — callers
    must FLAG the measurement, not publish the floored values (a 1e-9
    floor silently becomes "1.6e10 tok/s" downstream). The floored
    per-iteration value is still returned so callers can avoid division
    by zero while reporting the failure.
    """
    if k_long <= k_short:
        raise ValueError("k_long must exceed k_short")
    slope = (t_long - t_short) / (k_long - k_short)
    ok = slope > 0
    per_iter = max(slope, 1e-9)
    overhead = max(t_short - k_short * per_iter, 0.0)
    return per_iter, overhead, ok

"""Virtual-device configuration that works on every supported jax.

One spelling for "give me a CPU backend with N virtual devices" (the
multi-chip test/dryrun substrate): jax >= 0.6 has the
``jax_num_cpu_devices`` config option; jax 0.4.x only honors the
``--xla_force_host_platform_device_count`` XLA flag, which is read at
backend initialization — so either spelling must run BEFORE first device
use (backends initialize lazily; importing jax is safe, touching
``jax.devices()`` is not).
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int) -> None:
    """Force the CPU backend with ``n`` virtual devices. Call before any
    device use; raises RuntimeError (from jax) if the backend is already
    initialized with the config-option path, and silently has no effect
    in the XLA_FLAGS path (the flag is simply never re-read) — callers
    that can proceed on fewer devices should verify ``jax.devices()``."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # jax 0.4.x: no such option — use the XLA flag
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={n}"
        if opt not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} {opt}".strip()

"""KafkaStream: the end-to-end ingest pipeline.

This is the TPU-native replacement for the reference's entire hot path —
`KafkaDataset.__iter__` + DataLoader collation + `auto_commit`
(/root/reference/src/kafka_dataset.py:147-171, /root/reference/src/auto_commit.py:22-72)
— re-architected for an accelerator consumer:

    stream = KafkaStream(consumer, processor, batch_size=256, mesh=mesh)
    for batch, token in stream:
        loss = train_step(batch.data)       # pjit'd, async dispatch
        token.commit(wait_for=loss)         # barrier, then commit THIS batch

Architecture (one background thread per stream):

    poll -> ledger.fetched -> processor (thread pool) -> batcher
         -> device transfer (jax dispatch, overlaps with user's step)
         -> bounded queue (depth = prefetch, provides backpressure)
    main thread: dequeue -> mint CommitToken -> yield

The reference's multiprocessing design exists because CPython + torch force
process-level parallelism, which in turn forces the signal-based commit RPC
(SURVEY.md §1 "signature architectural fact"). Here the poll loop is I/O-bound
(releases the GIL), transforms run in a thread pool, and the heavy compute is
on the TPU — so one process per host suffices, commits run synchronously on
the stream owner's thread, and the entire signal/worker-correspondence hack
disappears.
"""

from __future__ import annotations

import logging
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from time import monotonic, time
from typing import Any, Iterator, Sequence

import jax

from torchkafka_tpu.commit import CommitBarrier, CommitSequencer, CommitToken, OffsetLedger
from torchkafka_tpu.errors import ConsumerClosedError
from torchkafka_tpu.parallel.mesh import global_batch
from torchkafka_tpu.source.consumer import Consumer
from torchkafka_tpu.transform.batcher import Batch, Batcher
from torchkafka_tpu.transform.processor import Processor
from torchkafka_tpu.utils.metrics import StreamMetrics
from torchkafka_tpu.utils.tracing import ingest_lag_ms

_logger = logging.getLogger(__name__)

_END = object()


class KafkaStream:
    """Iterator of (Batch, CommitToken) over a Kafka-like consumer.

    Parameters
    ----------
    consumer: any Consumer-protocol transport.
    processor: record -> pytree of fixed-shape np arrays, or None to drop
        (the reference's `_process` contract,
        /root/reference/src/kafka_dataset.py:173-186).
    batch_size: host-local rows per batch (global batch = this x process_count).
    mesh / data_axis: if given, batches are assembled into global jax.Arrays
        sharded over the mesh's data axis; else `jax.device_put` locally
        (or left as NumPy with to_device=False).
    pad_policy: 'block' (only full batches) or 'pad' (flush emits a padded
        tail with valid_count).
    prefetch: max batches in flight ahead of the consumer (double buffering
        at the default of 2). ``prefetch=0`` selects synchronous mode: no
        producer thread at all — poll/decode run inline in ``__next__`` on
        the caller's thread. Loses compute/ingest overlap, but also loses
        all queue/GIL handoff cost; fastest when the step is cheap relative
        to decode (pure-ingest workloads), and the mode to use when the
        caller forks (threads don't survive fork).
    idle_timeout_ms: if set, the stream ends after this long with no new
        records (flushing the tail under 'pad'); if None, it streams forever.
    transform_threads: >0 runs the processor in a thread pool (order
        preserved); numpy-heavy processors release the GIL and scale.
    on_processor_error: what a RAISING processor does to the stream.
        'raise' (default): the error surfaces on the consuming thread and
        ends the stream — malformed data is a bug until declared otherwise.
        'drop': the record is dropped exactly like a ``None`` return (its
        offset retires so the commit watermark keeps advancing), the error
        is counted in ``metrics.processor_errors`` and logged, and the
        stream continues — the poison-pill policy. For a CHUNKED processor
        the whole failing chunk drops (the chunk call is all-or-nothing).
        'quarantine': requires ``quarantine=``; each failure spends the
        record's retry budget (in-place re-attempts for transient
        processing faults), and once the budget is gone the record is
        dead-lettered with an ACKNOWLEDGED produce before its offset
        retires (counted in ``metrics.quarantined``) — so the committed
        watermark never covers a record that is neither processed nor
        durably quarantined. A failed DLQ produce fail-stops the stream
        (``OutputDeliveryError``, crash-before-commit) — the discipline
        'drop'+``dead_letter`` deliberately does NOT give you (there, a
        broken DLQ loses the copy but keeps ingest alive). Per-record
        processors only: a chunked processor's all-or-nothing call has no
        per-record failure to budget.
    dead_letter: optional ``(record, exception) -> None`` callback invoked
        for each record dropped by the 'drop' policy — wire it to a DLQ
        producer, a file, or a metrics sink. Exceptions it raises are
        logged and swallowed (a broken DLQ must not take down ingest).
    quarantine: a ``resilience.PoisonQuarantine`` (producer + DLQ topic +
        retry budget), required by ``on_processor_error='quarantine'``.
    buckets: length-bucket widths (e.g. ``(64, 128, 512)``) for RAGGED
        record streams: the (per-record) processor returns variable-length
        1-D rows; each lands in the smallest bucket that fits (longer than
        the largest truncates, like ``fixed_width``) and batches emit as
        ``{"tokens": [B, W], "length": [B]}`` per width — one static XLA
        shape per bucket instead of padding everything to the maximum.
        All buckets share the stream's ledger, so commits stay exact under
        out-of-order emission across buckets (transform/bucket.py).
    bucket_pad_value: fill value for intra-bucket padding.
    barrier: override the commit barrier. Default: a plain CommitBarrier
        single-process, and a BarrierWatchdog-wrapped one (exit 42 on
        timeout) on multi-process pods — a dead member must fail the pod
        closed and restartable, not wedge the collective forever.
    barrier_timeout_s / on_barrier_timeout: the default pod watchdog's
        timeout and optional extra callback (ignored when ``barrier`` is
        passed explicitly).
    clock: seconds-since-epoch clock for the ``ingest_lag_ms`` gauge
        (record append time -> poll time); default ``time.time``. Inject a
        ``resilience.ManualClock.now`` (with records produced at explicit
        ``timestamp_ms``) and consumer lag becomes exactly testable
        instead of wall-clock-dependent (utils.tracing.ingest_lag_ms).
    """

    def __init__(
        self,
        consumer: Consumer,
        processor: Processor,
        batch_size: int,
        *,
        mesh: jax.sharding.Mesh | None = None,
        data_axis: str | Sequence[str] = "data",
        pad_policy: str = "block",
        prefetch: int = 2,
        max_poll_records: int = 1024,
        poll_timeout_ms: int = 100,
        idle_timeout_ms: int | None = None,
        transform_threads: int = 0,
        to_device: bool = True,
        barrier: CommitBarrier | None = None,
        barrier_timeout_s: float = 300.0,
        on_barrier_timeout: Any | None = None,
        owns_consumer: bool = False,
        on_processor_error: str = "raise",
        dead_letter: Any | None = None,
        quarantine: Any | None = None,
        buckets: Any | None = None,
        bucket_pad_value: int = 0,
        clock: Any | None = None,
    ) -> None:
        if on_processor_error not in ("raise", "drop", "quarantine"):
            raise ValueError(
                "on_processor_error must be 'raise'|'drop'|'quarantine', "
                f"got {on_processor_error!r}"
            )
        if (on_processor_error == "quarantine") != (quarantine is not None):
            raise ValueError(
                "quarantine= and on_processor_error='quarantine' go "
                "together (the policy needs a DLQ route; a route needs "
                "the policy)"
            )
        self._consumer = consumer
        self._processor = processor
        self._chunked = bool(getattr(processor, "chunked", False))
        if quarantine is not None and self._chunked:
            raise ValueError(
                "on_processor_error='quarantine' needs a per-record "
                "processor: a chunked processor's all-or-nothing call has "
                "no per-record failure to budget (use 'drop' or 'raise')"
            )
        self._mesh = mesh
        self._data_axis = data_axis
        self._to_device = to_device
        self._max_poll = max_poll_records
        self._poll_timeout_ms = poll_timeout_ms
        self._idle_timeout_ms = idle_timeout_ms
        self._owns_consumer = owns_consumer
        self._clock = clock or time
        self._on_processor_error = on_processor_error
        self._dead_letter = dead_letter
        self._quarantine = quarantine
        if barrier is not None:
            self._barrier = barrier
        elif jax.process_count() > 1:
            # Multi-process pods get a watchdog-wrapped barrier BY DEFAULT
            # (VERDICT r2): a dead pod member otherwise wedges the commit
            # collective forever. Timing out is fail-closed — nothing was
            # committed, so exiting (42) and restarting from the last commit
            # loses no records; Kafka re-delivers the uncommitted tail.
            from torchkafka_tpu.parallel.multihost import BarrierWatchdog

            self._barrier = BarrierWatchdog(
                CommitBarrier(),
                timeout_s=barrier_timeout_s,
                on_timeout=on_barrier_timeout,
                exit_on_timeout=True,
            )
        else:
            self._barrier = CommitBarrier()
        self.metrics = StreamMetrics()
        self._ledger = OffsetLedger()
        if buckets is not None:
            if self._chunked:
                raise ValueError(
                    "buckets= requires a per-record processor returning "
                    "variable-length 1-D rows; chunked processors emit "
                    "fixed shapes already"
                )
            from torchkafka_tpu.transform.bucket import BucketBatcher

            self._batcher = BucketBatcher(
                batch_size, buckets, self._ledger, pad_policy=pad_policy,
                pad_value=bucket_pad_value,
            )
        else:
            self._batcher = Batcher(
                batch_size, self._ledger, pad_policy=pad_policy
            )
        self._sequencer = CommitSequencer()
        self._sync = prefetch == 0
        self._ready: list[Batch] = []  # sync mode: decoded-but-unyielded batches
        self._idle_since: float | None = None
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._pool = (
            ThreadPoolExecutor(max_workers=transform_threads, thread_name_prefix="tk-transform")
            if transform_threads > 0
            else None
        )
        self._thread = threading.Thread(
            target=self._produce_loop, name="tk-stream", daemon=True
        )
        self._started = False
        self._exhausted = False
        self._commit_pool: ThreadPoolExecutor | None = None

    def _commit_executor(self) -> ThreadPoolExecutor:
        """Single FIFO thread for token.commit_async (order-preserving)."""
        if self._commit_pool is None:
            self._commit_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tk-commit"
            )
        return self._commit_pool

    # ------------------------------------------------------------ producer

    def _put(self, item: Any) -> None:
        """Enqueue with backpressure, aborting if the stream is stopping."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _to_dev(self, batch: Batch) -> Batch:
        """Move a host batch toward the device (async dispatch)."""
        if self._to_device:
            if self._mesh is not None:
                data = global_batch(batch.data, self._mesh, self._data_axis)
            else:
                data = jax.tree_util.tree_map(jax.device_put, batch.data)
            batch = Batch(data=data, valid_count=batch.valid_count, offsets=batch.offsets)
        self.metrics.batches.add(1)
        return batch

    def _ship(self, batch: Batch) -> None:
        """Device transfer + enqueue. Runs on the producer thread so
        transfers overlap the consumer's step."""
        self._put(self._to_dev(batch))

    def _drop_errored(self, record, exc: Exception, quiet: bool = False) -> None:
        """The 'drop' policy for one failing record: count, log, DLQ.
        ``quiet`` skips the per-record log (chunk drops log once)."""
        self.metrics.processor_errors.add(1)
        if not quiet:
            _logger.warning(
                "processor raised on %s offset %d; dropping (%s)",
                record.tp, record.offset, exc,
            )
        if self._dead_letter is not None:
            try:
                self._dead_letter(record, exc)
            except Exception:  # noqa: BLE001 - a broken DLQ must not kill ingest
                # Swallowed by contract, but never SILENTLY: the counter
                # puts a broken DLQ on the /metrics endpoint (the record
                # really is lost to the DLQ — that must page someone, not
                # scroll past in stderr).
                self.metrics.dlq_delivery_failures.add(1)
                _logger.exception("dead_letter callback raised; record lost to DLQ")

    def _apply(self, record):
        """Processor with the error policy applied; an error under 'drop'
        becomes the None-drop contract (offset retires, stream continues).
        Under 'quarantine' the record is re-attempted in place while its
        budget lasts, then dead-lettered (acknowledged) and retired; a
        failed DLQ produce raises OutputDeliveryError through the normal
        sticky-death path — fail-stop, crash-before-commit."""
        while True:
            try:
                return self._processor(record)
            except Exception as e:  # noqa: BLE001 - policy decides
                if self._on_processor_error == "raise":
                    raise
                if self._on_processor_error == "quarantine":
                    self.metrics.processor_errors.add(1)
                    if not self._quarantine.note_failure(record, e):
                        continue  # budget left: transient until proven poison
                    self.metrics.quarantined.add(1)
                    _logger.warning(
                        "poison record %s offset %d dead-lettered to %r; "
                        "offset retires (%s)",
                        record.tp, record.offset,
                        self._quarantine.topic, e,
                    )
                    return None  # resolved: retires like a drop
                self._drop_errored(record, e)
                return None

    def _process_chunk(self, records) -> list[Batch]:
        """One poll chunk through ledger + transform + batcher. Shared by the
        threaded producer loop and the synchronous path."""
        self.metrics.records.add(len(records))
        newest = records[-1].timestamp_ms
        if newest:
            # Through the shared helper + the injectable clock, never a
            # bare wall-clock read: ManualClock tests pin lag exactly.
            self.metrics.ingest_lag_ms.set(
                ingest_lag_ms(newest, clock=self._clock)
            )
        self._ledger.fetched_many(records)
        if self._chunked:
            # Vectorized path: one processor call per poll chunk, one
            # slice-copy per emitted batch — the throughput hot path.
            try:
                stacked, keep = self._processor(records)
            except Exception as e:  # noqa: BLE001 - policy decides
                if self._on_processor_error == "raise":
                    raise
                # The chunk call is all-or-nothing: the whole chunk drops.
                # ONE log line for the chunk (a 1024-record poll would
                # otherwise emit 1024 identical warnings per bad record);
                # DLQ + metrics still run per record.
                _logger.warning(
                    "chunk processor raised; dropping %d records "
                    "(%s offsets %d-%d) (%s)",
                    len(records), records[0].tp, records[0].offset,
                    records[-1].offset, e,
                )
                for r in records:
                    self._drop_errored(r, e, quiet=True)
                stacked, keep = None, None
            if keep is not None:
                self.metrics.dropped.add(int(len(keep) - keep.sum()))
            elif stacked is None:
                self.metrics.dropped.add(len(records))
            # stacked=None (whole chunk dropped) is handled by the batcher:
            # it retires every offset so the commit watermark can't freeze.
            return self._batcher.add_many(stacked, records, keep)
        if self._pool is not None:
            # Lazy: results stream out in order as workers finish, so a
            # batch ships as soon as it fills instead of waiting for the
            # whole poll chunk to transform.
            elements = self._pool.map(self._apply, records)
        else:
            elements = (self._apply(r) for r in records)
        outs = []
        for r, el in zip(records, elements):
            if el is None:
                self.metrics.dropped.add(1)
            out = self._batcher.add(el, r)
            if out is not None:
                outs.append(out)
        return outs

    def _produce_loop(self) -> None:
        last_data = monotonic()
        try:
            while not self._stop.is_set():
                try:
                    records = self._consumer.poll(
                        max_records=self._max_poll, timeout_ms=self._poll_timeout_ms
                    )
                except ConsumerClosedError:
                    break  # clean end: consumer closed under us
                if not records:
                    if (
                        self._idle_timeout_ms is not None
                        and (monotonic() - last_data) * 1000 >= self._idle_timeout_ms
                    ):
                        break
                    continue
                last_data = monotonic()
                for out in self._process_chunk(records):
                    self._ship(out)
            for tail in self._batcher.flush_tails():
                self._ship(tail)
        except BaseException as e:  # noqa: BLE001 - re-raised on the main thread
            self._error = e
        finally:
            self._put(_END)

    # ------------------------------------------------------------ consumer

    def __iter__(self) -> Iterator[tuple[Batch, CommitToken]]:
        return self

    def _next_sync(self) -> tuple[Batch, CommitToken]:
        """prefetch=0: poll/decode inline on the caller's thread."""
        while not self._ready:
            if self._stop.is_set():
                raise StopIteration
            try:
                records = self._consumer.poll(
                    max_records=self._max_poll, timeout_ms=self._poll_timeout_ms
                )
            except ConsumerClosedError:
                records = []
                self._stop.set()
            if records:
                self._idle_since = None
                try:
                    self._ready.extend(self._process_chunk(records))
                except BaseException as e:  # noqa: BLE001 - sticky, then re-raised
                    # Same sticky-death contract as the threaded path: a
                    # processor error ENDS the stream. Without this, a
                    # caller that catches the error and keeps iterating
                    # would silently resume past a poisoned chunk whose
                    # offsets are half-resolved — completed batches lost,
                    # commit watermark frozen at the poison offset.
                    self._error = e
                    self._exhausted = True
                    self._stop.set()
                    raise
                continue
            now = monotonic()
            if self._idle_since is None:
                self._idle_since = now
            if self._stop.is_set() or (
                self._idle_timeout_ms is not None
                and (now - self._idle_since) * 1000 >= self._idle_timeout_ms
            ):
                tails = self._batcher.flush_tails()
                self._exhausted = True
                if not tails:
                    raise StopIteration
                self._ready.extend(tails)
        return self._mint(self._to_dev(self._ready.pop(0)))

    def __next__(self) -> tuple[Batch, CommitToken]:
        if self._exhausted and not self._ready:
            # Sticky: the _END sentinel is consumed only once; without this a
            # second iteration attempt would block forever on an empty queue.
            if self._error is not None:
                raise self._error
            raise StopIteration
        if self._sync:
            return self._next_sync()
        if not self._started:
            self._started = True
            self._thread.start()
        while True:
            try:
                item = self._queue.get(timeout=0.5)
                break
            except queue.Empty:
                if self._error is not None:
                    self._exhausted = True
                    raise self._error
                if self._stop.is_set():
                    self._exhausted = True
                    raise StopIteration
        if item is _END:
            self._exhausted = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return self._mint(item)

    def _mint(self, batch: Batch) -> tuple[Batch, CommitToken]:
        token = CommitToken(
            self._consumer,
            batch.offsets,
            self._sequencer,
            barrier=self._barrier,
            on_commit=self._record_commit,
            executor=self._commit_executor,
        )
        return batch, token

    def _record_commit(self, latency_s: float, ok: bool) -> None:
        if ok:
            self.metrics.commit_latency.observe(latency_s)
        else:
            self.metrics.commit_failures.add(1)

    # ----------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Stop the stream. Never commits on its own — in-flight batches
        re-deliver (the reference's close contract,
        /root/reference/src/kafka_dataset.py:89) — but commits the USER
        already requested via commit_async are drained, not dropped."""
        self._stop.set()
        if self._started:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                _logger.warning(
                    "KafkaStream producer thread still alive after 5s join; "
                    "a wedged consumer poll is leaking a daemon thread that "
                    "holds the consumer"
                )
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
        if self._commit_pool is not None:
            self._commit_pool.shutdown(wait=True)
        if self._owns_consumer:
            self._consumer.close()

    def __enter__(self) -> "KafkaStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def stream(consumer: Consumer, processor: Processor, batch_size: int, **kw) -> KafkaStream:
    """Functional spelling of KafkaStream(...)."""
    return KafkaStream(consumer, processor, batch_size, **kw)

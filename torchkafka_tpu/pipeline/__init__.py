"""Pipeline layer: the user-facing streaming loop."""

from torchkafka_tpu.pipeline.stream import KafkaStream, stream

__all__ = ["KafkaStream", "stream"]

"""Fused blocked softmax cross-entropy — the lm_head + loss hot path.

Net-new vs the reference (no model code in its tree, SURVEY.md §2). This op
exists for one TPU reason: on a vocab-sized head the naive loss

    logits = x @ W            # [B, S, V] f32
    logp   = log_softmax(logits)
    nll    = -take_along_axis(logp, targets)

materialises two full ``[B, S, V]`` float32 tensors in HBM (4.2 GB each at
B=64, S=512, V=32k) and keeps one alive as the log_softmax residual for the
backward pass. For a small-d_model LM the head matmul is >half the model
FLOPs, so this traffic dominates the step — measured 18-32% MFU on the 45M
flagship before this op (PERF.md round 2).

Design (the standard fused-CE shape, e.g. the "blocked cross-entropy" in
large-vocab LM trainers, re-derived for XLA):

- **Scan over sequence blocks.** Each block computes ``[B, blk, V]`` logits
  (bf16 MXU matmul, f32 accumulate), reduces them to per-token logsumexp +
  target logit, and discards them. Peak HBM for the head is one block of
  logits instead of the full tensor.
- **Analytic gradients in the forward scan — no backward recompute.** The
  loss is scalar and its cotangent ``g`` enters linearly, so
  ``dlogits = (softmax(logits) - onehot(targets)) * mask`` can be computed
  while the block's logits are still live, contracted immediately into
  ``dx`` ([B, S, D]) and ``dW`` ([D, V], f32 accumulator in the scan
  carry), and simply scaled by ``g / count`` in the VJP. Total head matmul
  cost is exactly 3 passes (fwd + dx + dW) — the same FLOPs as unfused
  AD — with zero ``[B, S, V]`` residuals and zero recompute (a
  ``jax.checkpoint``-based blocking would pay a 4th pass).
- **Sharding-transparent.** Everything is ``jnp`` under ``jit``: batch
  stays sharded over data/fsdp (the scan iterates sequence blocks only),
  and a tp-sharded ``W`` shards each block's logits over vocab with XLA
  inserting the logsumexp psum. The one layout this op must NOT be used
  with is sequence parallelism (sp>1): the scan would serialise over the
  sharded axis. ``Transformer.loss`` guards that case and keeps the dense
  path (ring/ulysses activations never materialise full-S logits anyway).

The primal path (loss value only, e.g. eval) skips the gradient work.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

# Peak bytes of f32 block logits to aim for when auto-picking a block size.
# 256 MB keeps the per-block matmul M-dim (B*blk) MXU-sized at realistic
# batch/vocab while bounding HBM pressure; measured insensitive ±2× on v5e.
_AUTO_BLOCK_BYTES = 256 * 1024 * 1024


def auto_block_size(batch: int, seq: int, vocab: int) -> int:
    """Largest power-of-two sequence block with ≤ _AUTO_BLOCK_BYTES of f32
    block logits, clamped to [16, seq]."""
    budget = max(1, _AUTO_BLOCK_BYTES // (4 * batch * max(vocab, 1)))
    blk = 2 ** int(math.floor(math.log2(budget))) if budget > 1 else 1
    return max(16, min(seq, blk))


def dense_softmax_xent(x, w, targets, mask, compute_dtype=jnp.bfloat16):
    """Reference implementation: full-logits masked-mean CE. Used as the
    fallback (sp>1 / quantized heads) and as the test oracle."""
    logits = jnp.einsum(
        "bsd,dv->bsv", x.astype(compute_dtype), w.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = mask.astype(nll.dtype)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)


def _resolve_block(block_size, batch, seq, vocab) -> int:
    """None → auto. 0/negative is an error here, NOT a dense fallback: the
    'ce_block_size=0 disables fusion' contract lives in Transformer, which
    routes to the dense path before this op is ever called."""
    if block_size is None:
        return auto_block_size(batch, seq, vocab)
    if block_size <= 0:
        raise ValueError(
            f"block_size must be a positive int or None (auto), got "
            f"{block_size}; use the dense CE for an unblocked loss"
        )
    return block_size


def _pad_blocks(x, targets, mask, block):
    """Pad S up to a multiple of ``block`` with mask-0 rows and reshape to
    scan layout [nb, B, block, ...]."""
    b, s, _ = x.shape
    nb = -(-s // block)
    pad = nb * block - s
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    xb = x.reshape(b, nb, block, x.shape[-1]).transpose(1, 0, 2, 3)
    tb = targets.reshape(b, nb, block).transpose(1, 0, 2)
    mb = mask.reshape(b, nb, block).transpose(1, 0, 2)
    return xb, tb, mb, pad


def _block_stats(xx, wc, tt, mm, compute_dtype):
    """One block's logits → (f32 logits, logsumexp, masked nll sum)."""
    logits = jnp.einsum(
        "bsd,dv->bsv", xx.astype(compute_dtype), wc,
        preferred_element_type=jnp.float32,
    )
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, tt[..., None], axis=-1)[..., 0]
    nll_sum = jnp.sum((lse - tgt) * mm)
    return logits, lse, nll_sum


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_softmax_xent(
    x, w, targets, mask, block_size=None, compute_dtype=jnp.bfloat16
):
    """Masked-mean next-token CE over a vocab head, blocked over sequence.

    x: [B, S, D] trunk output; w: [D, V] head (master dtype — cast to
    ``compute_dtype`` inside so dW comes back in master precision);
    targets: [B, S] int; mask: [B, S] (0 ⇒ position excluded).
    Matches ``dense_softmax_xent`` to f32-reduction tolerance.
    """
    b, s, _ = x.shape
    blk = _resolve_block(block_size, b, s, w.shape[-1])
    wc = w.astype(compute_dtype)
    xb, tb, mb, _ = _pad_blocks(x, targets, mask.astype(jnp.float32), blk)

    def body(tot, inp):
        xx, tt, mm = inp
        _, _, nll_sum = _block_stats(xx, wc, tt, mm, compute_dtype)
        return tot + nll_sum, None

    tot, _ = lax.scan(body, jnp.float32(0.0), (xb, tb, mb))
    return tot / jnp.maximum(mask.astype(jnp.float32).sum(), 1.0)


def _fused_fwd(x, w, targets, mask, block_size, compute_dtype):
    b, s, _ = x.shape
    v = w.shape[-1]
    blk = _resolve_block(block_size, b, s, v)
    wc = w.astype(compute_dtype)
    xb, tb, mb, pad = _pad_blocks(x, targets, mask.astype(jnp.float32), blk)

    def body(carry, inp):
        tot, dw = carry
        xx, tt, mm = inp
        logits, lse, nll_sum = _block_stats(xx, wc, tt, mm, compute_dtype)
        # d(nll_sum)/d(logits), before the 1/count and cotangent scaling
        # applied in the bwd rule (both enter linearly).
        p = jnp.exp(logits - lse[..., None])
        dlog = (
            (p - jax.nn.one_hot(tt, v, dtype=jnp.float32)) * mm[..., None]
        ).astype(compute_dtype)
        dx = jnp.einsum(
            "bsv,dv->bsd", dlog, wc, preferred_element_type=jnp.float32
        )
        dw = dw + jnp.einsum(
            "bsd,bsv->dv", xx.astype(compute_dtype), dlog,
            preferred_element_type=jnp.float32,
        )
        return (tot + nll_sum, dw), dx

    dw0 = jnp.zeros(w.shape, jnp.float32)
    (tot, dw), dxb = lax.scan(body, (jnp.float32(0.0), dw0), (xb, tb, mb))
    cnt = jnp.maximum(mask.astype(jnp.float32).sum(), 1.0)
    dx = dxb.transpose(1, 0, 2, 3).reshape(b, s + pad, x.shape[-1])[:, :s]
    # Zero-size sentinels carry the primal dtypes into bwd (raw dtypes are
    # not valid residual-pytree leaves).
    x_like = jnp.zeros((0,), x.dtype)
    w_like = jnp.zeros((0,), w.dtype)
    return tot / cnt, (dx, dw, cnt, x_like, w_like)


def _fused_bwd(block_size, compute_dtype, res, g):
    dx, dw, cnt, x_like, w_like = res
    scale = (g / cnt).astype(jnp.float32)
    return (
        (dx * scale).astype(x_like.dtype),
        (dw * scale).astype(w_like.dtype),
        None,  # integer targets
        None,  # mask treated as non-differentiable selection weights
    )


fused_softmax_xent.defvjp(_fused_fwd, _fused_bwd)

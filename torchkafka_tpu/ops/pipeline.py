"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Net-new vs the reference (no model code, SURVEY.md §2 parallelism table).
The stacked-layer representation ([L, ...] params + one scanned body, see
models/transformer.py) pipelines naturally: shard the layer axis over
``pp`` so each stage owns L/P consecutive layers, split the batch into
microbatches, and run the classic GPipe schedule — M + P - 1 ticks, each
stage applying its local layer stack and handing its activation to the next
stage over ``lax.ppermute`` (one ICI hop on a TPU torus).

Manual collectives are confined to the ``pp`` axis via partial-manual
``shard_map`` (``axis_names={'pp'}``): tensor/data/fsdp sharding inside the
stage body stays automatic, so the same layer code composes with tp/sp/ep
exactly as in the non-pipelined path. The whole schedule is built from
``lax.scan`` + ``ppermute`` + ``where``, all with transpose rules, so
``jax.grad`` through the pipeline just works (backward replays the schedule
in reverse).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from torchkafka_tpu.ops._compat import shard_map  # noqa: E402


def gpipe(
    layer_fn: Callable[[jax.Array, Any], Any],
    layer_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    microbatches: int | None = None,
    extra_manual: frozenset[str] | set[str] = frozenset(),
    act_spec: P | None = None,
    collect_stats: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Pipelined equivalent of ``lax.scan(layer_fn)`` over stacked layers.

    layer_fn(act, one_layer) -> act; layer_params: pytree with leading layer
    dim L (sharded over ``axis``: stage p owns layers [p·L/P, (p+1)·L/P));
    x: [B, ...] activations. Returns the same value as the sequential scan,
    bitwise up to reduction order.

    ``microbatches`` (default = pipeline depth P) must divide B; deeper
    M reduces the bubble fraction (P-1)/(M+P-1) at the cost of smaller
    per-tick matmuls.

    ``extra_manual``/``act_spec``: axes the layer body handles manually
    itself (e.g. 'sp' when the body runs ring attention — a nested
    shard_map over the same axis is illegal, so the stage binds it and the
    body's collectives run directly). ``act_spec`` is the PartitionSpec of
    one activation [B, ...] over those axes; its batch entry is ignored.

    ``collect_stats``: layer_fn instead returns (act, stats) with stats a
    fixed-shape f32 array of per-layer TOKEN-SUMMED statistics (e.g. MoE
    router load sums — sums, not means, so they add across microbatches).
    gpipe then also returns a stacked [L, *stats] array holding, per layer,
    the statistic summed over the full batch: each stage accumulates its
    local layers' stats across its valid schedule ticks (warmup/drain ticks
    process garbage and are masked out), and a psum over ``axis`` (and any
    ``extra_manual`` axes that shard tokens, e.g. 'sp') assembles the
    global view, replicated on every stage.
    """
    n_stages = mesh.shape[axis]
    if n_stages == 1:
        def seq_body(a, layer):
            out = layer_fn(a, layer)
            return out if collect_stats else (out, None)

        x_out, ys = lax.scan(seq_body, x, layer_params)
        return (x_out, ys) if collect_stats else x_out
    m = microbatches if microbatches is not None else n_stages
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} not divisible by microbatches {m}")

    orig_dtype = x.dtype

    def stage_body(params_local: Any, x_mb_f32: jax.Array):
        # The shard_map boundary is f32 (cast back immediately): x is
        # replicated over pp, so its cotangent is an all-reduce across the
        # stages — and XLA's CPU AllReducePromotion pass miscompiles bf16
        # all-reduces. Stage-internal compute still runs in the caller's
        # dtype; ppermute (the only steady-state collective) is unaffected.
        x_mb = x_mb_f32.astype(orig_dtype)
        stage = lax.axis_index(axis)
        n_local = jax.tree_util.tree_leaves(params_local)[0].shape[0]

        def apply_stage(act):
            def body(a, layer):
                if collect_stats:
                    return layer_fn(a, layer)
                return layer_fn(a, layer), None

            return lax.scan(body, act, params_local)

        out_buf = jnp.zeros_like(x_mb)  # [M, mb, ...]
        act = jnp.zeros_like(x_mb[0])
        if collect_stats:
            st_shape = jax.eval_shape(
                lambda a: apply_stage(a)[1], act
            )
            stats_acc = jnp.zeros(st_shape.shape, jnp.float32)
        else:
            stats_acc = jnp.float32(0.0)  # placeholder carry leaf

        def tick(carry, t):
            act, out_buf, stats_acc = carry
            # Stage 0 ingests microbatch t (harmless clipped re-read after M).
            incoming = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), keepdims=False
            )
            act = jnp.where(stage == 0, incoming, act)
            act, stats = apply_stage(act)
            if collect_stats:
                # Stage p holds microbatch t-p at tick t; outside [0, M)
                # it is processing warmup zeros or drain re-reads whose
                # statistics must not count.
                valid = (t >= stage) & (t - stage < m)
                stats_acc = stats_acc + jnp.where(valid, 1.0, 0.0) * stats
            # Last stage retires microbatch t-(P-1).
            idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (idx >= 0)
            safe = jnp.clip(idx, 0, m - 1)
            current = lax.dynamic_index_in_dim(out_buf, safe, keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, act, current), safe, 0
            )
            # Hand activations downstream: stage p -> p+1.
            act = lax.ppermute(
                act, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (act, out_buf, stats_acc), None

        (act, out_buf, stats_acc), _ = lax.scan(
            tick, (act, out_buf, stats_acc), jnp.arange(m + n_stages - 1)
        )
        # Replicate the last stage's result across the pp axis (f32 — see
        # the boundary note above).
        masked = jnp.where(
            stage == n_stages - 1, out_buf, jnp.zeros_like(out_buf)
        ).astype(jnp.float32)
        out = lax.psum(masked, axis)
        if not collect_stats:
            return out
        # Place each stage's [L/P, ...] stats at its layer offset in the
        # full [L, ...] array; psum over pp assembles + replicates, psum
        # over manual token-sharding axes (sp) globalises the token sums.
        full = jnp.zeros((n_local * n_stages,) + stats_acc.shape[1:],
                         jnp.float32)
        full = lax.dynamic_update_slice(
            full, stats_acc,
            (stage * n_local,) + (0,) * (stats_acc.ndim - 1),
        )
        reduce_axes = (axis,) + tuple(a for a in extra_manual)
        return out, lax.psum(full, reduce_axes)

    # [B, ...] -> [M, B/M, ...]; the microbatch loop runs inside the stages.
    x_mb = x.reshape(m, batch // m, *x.shape[1:]).astype(jnp.float32)
    layer_specs = jax.tree_util.tree_map(lambda _: P(axis), layer_params)
    if act_spec is not None:
        # [B, d1, d2, ...] spec -> [M, mb, d1, d2, ...] spec.
        x_spec = P(None, None, *tuple(act_spec)[1:])
    else:
        x_spec = P()
    result = shard_map(
        stage_body,
        mesh=mesh,
        in_specs=(layer_specs, x_spec),
        out_specs=(x_spec, P()) if collect_stats else x_spec,
        axis_names=frozenset({axis}) | frozenset(extra_manual),
        check_vma=False,
    )(layer_params, x_mb)
    out, stats = result if collect_stats else (result, None)
    out = out.reshape(batch, *x.shape[1:]).astype(orig_dtype)
    return (out, stats) if collect_stats else out

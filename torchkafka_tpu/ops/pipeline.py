"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Net-new vs the reference (no model code, SURVEY.md §2 parallelism table).
The stacked-layer representation ([L, ...] params + one scanned body, see
models/transformer.py) pipelines naturally: shard the layer axis over
``pp`` so each stage owns L/P consecutive layers, split the batch into
microbatches, and run the classic GPipe schedule — M + P - 1 ticks, each
stage applying its local layer stack and handing its activation to the next
stage over ``lax.ppermute`` (one ICI hop on a TPU torus).

Manual collectives are confined to the ``pp`` axis via partial-manual
``shard_map`` (``axis_names={'pp'}``): tensor/data/fsdp sharding inside the
stage body stays automatic, so the same layer code composes with tp/sp/ep
exactly as in the non-pipelined path. The whole schedule is built from
``lax.scan`` + ``ppermute`` + ``where``, all with transpose rules, so
``jax.grad`` through the pipeline just works (backward replays the schedule
in reverse).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def gpipe(
    layer_fn: Callable[[jax.Array, Any], jax.Array],
    layer_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    microbatches: int | None = None,
    extra_manual: frozenset[str] | set[str] = frozenset(),
    act_spec: P | None = None,
) -> jax.Array:
    """Pipelined equivalent of ``lax.scan(layer_fn)`` over stacked layers.

    layer_fn(act, one_layer) -> act; layer_params: pytree with leading layer
    dim L (sharded over ``axis``: stage p owns layers [p·L/P, (p+1)·L/P));
    x: [B, ...] activations. Returns the same value as the sequential scan,
    bitwise up to reduction order.

    ``microbatches`` (default = pipeline depth P) must divide B; deeper
    M reduces the bubble fraction (P-1)/(M+P-1) at the cost of smaller
    per-tick matmuls.

    ``extra_manual``/``act_spec``: axes the layer body handles manually
    itself (e.g. 'sp' when the body runs ring attention — a nested
    shard_map over the same axis is illegal, so the stage binds it and the
    body's collectives run directly). ``act_spec`` is the PartitionSpec of
    one activation [B, ...] over those axes; its batch entry is ignored.
    """
    n_stages = mesh.shape[axis]
    if n_stages == 1:
        def seq_body(a, layer):
            return layer_fn(a, layer), None

        return lax.scan(seq_body, x, layer_params)[0]
    m = microbatches if microbatches is not None else n_stages
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} not divisible by microbatches {m}")

    orig_dtype = x.dtype

    def stage_body(params_local: Any, x_mb_f32: jax.Array) -> jax.Array:
        # The shard_map boundary is f32 (cast back immediately): x is
        # replicated over pp, so its cotangent is an all-reduce across the
        # stages — and XLA's CPU AllReducePromotion pass miscompiles bf16
        # all-reduces. Stage-internal compute still runs in the caller's
        # dtype; ppermute (the only steady-state collective) is unaffected.
        x_mb = x_mb_f32.astype(orig_dtype)
        stage = lax.axis_index(axis)

        def apply_stage(act):
            def body(a, layer):
                return layer_fn(a, layer), None

            return lax.scan(body, act, params_local)[0]

        out_buf = jnp.zeros_like(x_mb)  # [M, mb, ...]
        act = jnp.zeros_like(x_mb[0])

        def tick(carry, t):
            act, out_buf = carry
            # Stage 0 ingests microbatch t (harmless clipped re-read after M).
            incoming = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), keepdims=False
            )
            act = jnp.where(stage == 0, incoming, act)
            act = apply_stage(act)
            # Last stage retires microbatch t-(P-1).
            idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (idx >= 0)
            safe = jnp.clip(idx, 0, m - 1)
            current = lax.dynamic_index_in_dim(out_buf, safe, keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, act, current), safe, 0
            )
            # Hand activations downstream: stage p -> p+1.
            act = lax.ppermute(
                act, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (act, out_buf), None

        (act, out_buf), _ = lax.scan(
            tick, (act, out_buf), jnp.arange(m + n_stages - 1)
        )
        # Replicate the last stage's result across the pp axis (f32 — see
        # the boundary note above).
        masked = jnp.where(
            stage == n_stages - 1, out_buf, jnp.zeros_like(out_buf)
        ).astype(jnp.float32)
        return lax.psum(masked, axis)

    # [B, ...] -> [M, B/M, ...]; the microbatch loop runs inside the stages.
    x_mb = x.reshape(m, batch // m, *x.shape[1:]).astype(jnp.float32)
    layer_specs = jax.tree_util.tree_map(lambda _: P(axis), layer_params)
    if act_spec is not None:
        # [B, d1, d2, ...] spec -> [M, mb, d1, d2, ...] spec.
        x_spec = P(None, None, *tuple(act_spec)[1:])
    else:
        x_spec = P()
    out = shard_map(
        stage_body,
        mesh=mesh,
        in_specs=(layer_specs, x_spec),
        out_specs=x_spec,
        axis_names=frozenset({axis}) | frozenset(extra_manual),
        check_vma=False,
    )(layer_params, x_mb)
    return out.reshape(batch, *x.shape[1:]).astype(orig_dtype)

"""Pallas int8-KV decode attention kernels.

Two generations live here, both correctness-pinned by differential
tests against the scale-folded XLA read (exact to f32 reduction order):

**v1 ``int8_decode_attention`` (M-major cache [B, M, K, Dh]) — measured
SLOWER, kept as the recorded negative result.** The hypothesis it
tested (PERF.md, int8-KV section): the XLA spelling of the int8-KV
attention read materialises an int8→bf16 converted copy of the cache
instead of fusing the convert into the dot's HBM read, costing ~20%
equal-slot throughput vs a bf16 cache — so a kernel that streams int8
tiles HBM→VMEM directly (the in-VMEM convert is on-core work) should
win the bytes back. MEASURED (8B int8 weights, 96 slots, 192-token
budget): 85.1 ms/tick vs the XLA read's 46.8 — 1.8× SLOWER. Diagnosis:
the M-major layout puts the kv-head axis in the middle, so every
per-head slice ``cache[:, k, :]`` is strided and Mosaic's batched-dot
positional rule forces a per-head static loop of tiny [rep≤4, Dh]
dots over relaid-out operands — serialized on-core work that swamps
the saved HBM bytes.

**v2 ``int8_decode_attention_kmajor`` (K-major cache [B, K, M, Dh]) —
the redesign v1's postmortem called for.** Storing the pool K-major
makes every head's [M, Dh] tile a contiguous leading-axis slice, and
both dots collapse into ONE K-batched ``dot_general`` whose batch dims
sit at position 0 on each operand (Mosaic's requirement), so there is
no per-head loop and no in-VMEM relayout. A ``slot_block`` parameter
processes several slots per grid step — their (slot, head) axes merge
into the batch dim by a layout-free leading reshape — so each grid
step issues one large DMA (bb·K·M·Dh bytes) instead of v1's
one-small-DMA-per-slot structure, and Pallas double-buffers it across
the (B/bb,)-parallel grid.

MEASURED (v5e, 8B shapes). Isolated pool read, fori-chained slope over
alternating cache pairs: the kernel beats the XLA scale-folded read at
every shape tried — 59.4 µs vs 66.0 (1.11×, 655 GB/s) at B=96/M=192,
92.4 vs 120.8 µs (1.31×, 749 GB/s = 91% of peak) at B=16/M=2048. Full
serving tick (the number that matters): the win survives only at LONG
pools — M=2048 31.6→30.7 ms and M=1024 36.1→35.6 ms (exactly the
isolated delta), but M=192 REGRESSES 16.7→17.3 (B=16) and 46.7→49.2 ms
(B=96): the K-major update path plus the fusion break around a Pallas
call cost ~2.5 ms/tick regardless of pool length. Hence serve.py's
``kv_kernel="auto"`` engages the kernel only at pool length ≥ 1024;
v1's "XLA materialises a converted copy" diagnosis also did not
reproduce in-tick on this XLA version (the in-tick XLA read streams at
the isolated rate), so the remaining known upside is a dynamic-length
read (skip DMA beyond each slot's position — inexpressible in XLA).

**v3 ``int8_decode_attention_dynlen`` (K-major + per-slot watermarks) —
the SHIPPED serving kernel.** Same K-major layout and batched dots as
v2, but the pool stays in HBM (``memory_space=ANY``), the per-slot
watermarks arrive by scalar prefetch, and the kernel manually DMAs
M-blocks with double buffering and a flash-style online-softmax
recurrence — the per-slot block loop runs ``ceil((pos+1)/mb)`` times,
so positions beyond a slot's fill are NEVER FETCHED. HBM traffic then
scales with the actual fill instead of the pool size, which no XLA
spelling can do (static shapes make every read pool-shaped). Two
non-obvious pieces: (a) buffer parity is GLOBAL across the whole grid
(each program derives its starting parity from the prefetched
watermark prefix-sum) so that (b) each program's first block is DMA'd
by its PREDECESSOR during the predecessor's last-block compute
(sequential "arbitrary" grid; scratch persists across programs) —
without the cross-program prefetch, every slot began with a DMA stall
(measured +24% at full fill). MEASURED (v5e, 8B shapes, M=2048, B=16,
paired interleaved slopes): v2 full read 98.0 µs; v3 103.8 µs at
exactly-full (the online-softmax recurrence's cost), 51.0 µs at half
fill (1.92× v2), 62.5 µs at mixed fills (1.57×) — and continuous
batching lives at partial fills. Full tick (8B int8, 16 slots,
pool 2048, fill pinned to the 75% steady-state midpoint): XLA read
33.93 ms → v3 27.85 ms (+22% tok/s). serve.py ships v3 as the
``kv_kernel="auto"`` kernel at pools ≥ 1024; v2 remains the
fixed-shape record (and the differential-test reference).

Net-new vs the reference (no kernels in its tree, SURVEY.md §2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from torchkafka_tpu.ops.flash import _default_interpret, tpu_compiler_params

_NEG_INF = -1e30
# pl.ANY replaced pltpu.ANY (DeprecationWarning; the alias is slated for
# removal) — fall back for older jax.
_ANY = getattr(pl, "ANY", None) or (pltpu and pltpu.ANY)


def _kvattn_kernel(
    q_ref, kq_ref, ks_ref, vq_ref, vs_ref, mask_ref, o_ref, *,
    inv_sqrt_dh: float,
):
    q = q_ref[0]  # [K, rep, Dh] compute dtype
    # int8 tiles were DMA'd into VMEM at 1 byte/element — the convert
    # below is on-core work, not HBM traffic (the thing the kernel
    # exists to halve).
    kq = kq_ref[0].astype(q.dtype)  # [M, K, Dh]
    vq = vq_ref[0].astype(q.dtype)
    ks = ks_ref[0]  # [M, K] f32
    vs = vs_ref[0]
    mask = mask_ref[0, 0][None, :]  # [1, M]
    # STATIC loop over kv heads (K is small — 8 at the 8B shapes):
    # Mosaic's batched dot requires equal batch-dim positions, which the
    # [M, K, Dh] cache layout doesn't give; per-head 2-D dots sidestep it
    # and unroll fully at trace time.
    outs = []
    for k in range(q.shape[0]):
        s = jax.lax.dot_general(
            q[k], kq[:, k, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rep, M]
        s = s * ks[:, k][None, :] * inv_sqrt_dh
        s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        pw = (p * vs[:, k][None, :]).astype(q.dtype)
        outs.append(jax.lax.dot_general(
            pw, vq[:, k, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ))  # [rep, Dh]
    o_ref[0] = jnp.stack(outs).astype(o_ref.dtype)


def kernel_applicable(head_dim: int, max_len: int) -> bool:
    """Compiled-Mosaic tiling constraints: lane-aligned head_dim and
    sublane-aligned pool length (the (M, K)-trailing scale blocks need
    M % 8; Dh is the lane dim of the payload blocks). Interpret mode
    accepts anything; tests force it."""
    return head_dim % 128 == 0 and max_len % 8 == 0


# Per-grid-step int8 in-block byte budget. Measured on v5e (Mosaic
# compile + run): 4.2 MB of int8 in-blocks per step compiles and runs at
# full rate (M=2048 bb=1, M=1024 bb=2); 8.4 MB fails to compile. Set just
# above the known-good point.
_SLOT_BLOCK_BUDGET = 4_718_592


def kernel_feasible(n_kv: int, max_len: int, head_dim: int) -> bool:
    """True iff SOME slot block fits the VMEM budget — bb=1 is the floor,
    so feasibility is one slot's k+v int8 bytes within budget. Callers
    gate on this before engaging the kernel: past it, every slot_block
    choice (including 1) produces the in-block size that fails Mosaic
    compilation (see _SLOT_BLOCK_BUDGET)."""
    return 2 * n_kv * max_len * head_dim <= _SLOT_BLOCK_BUDGET


def _pick_slot_block(batch: int, n_kv: int, max_len: int, head_dim: int) -> int:
    """Largest slot block (≤8, dividing B) whose per-step working set —
    two int8 payload blocks, their bf16 converts, and double-buffered
    input windows — fits the measured VMEM budget. Larger bb is FASTER
    where it fits (M=192: bb=8 59 µs vs bb=1 80 µs — fewer grid steps
    amortize the per-step DMA issue cost)."""
    per_slot = 2 * n_kv * max_len * head_dim  # k+v int8 bytes
    for bb in (8, 4, 2, 1):
        if batch % bb == 0 and bb * per_slot <= _SLOT_BLOCK_BUDGET:
            return bb
    return 1


def _kvattn_kmajor_kernel(
    q_ref, kq_ref, ks_ref, vq_ref, vs_ref, mask_ref, o_ref, *,
    inv_sqrt_dh: float,
):
    bb, n_kv, rep, dh = q_ref.shape
    m = kq_ref.shape[2]
    g = bb * n_kv
    # Leading-axis merges are layout-free (the trailing sublane/lane pair
    # is untouched): (bb, K, ·, ·) → (bb·K, ·, ·) costs nothing.
    q = q_ref[...].reshape(g, rep, dh)
    kq = kq_ref[...].reshape(g, m, dh).astype(q.dtype)
    # ONE batched dot over all (slot, head) pairs — batch dims at
    # position 0 on both operands, Mosaic's batched-dot rule.
    s = jax.lax.dot_general(
        q, kq, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [G, rep, M]
    s = s.reshape(bb, n_kv, rep, m)
    ks = ks_ref[...]  # [bb, K, M] f32
    s = s * ks[:, :, None, :] * inv_sqrt_dh
    mask = mask_ref[...]  # [bb, 1, M]
    s = jnp.where(mask[:, :, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    vs = vs_ref[...]
    pw = (p * vs[:, :, None, :]).astype(q.dtype).reshape(g, rep, m)
    vq = vq_ref[...].reshape(g, m, dh).astype(q.dtype)
    o = jax.lax.dot_general(
        pw, vq, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [G, rep, Dh]
    o_ref[...] = o.reshape(bb, n_kv, rep, dh).astype(o_ref.dtype)


def int8_decode_attention_kmajor(
    q: jax.Array,
    ck_q: jax.Array,
    ck_s: jax.Array,
    cv_q: jax.Array,
    cv_s: jax.Array,
    valid: jax.Array,
    *,
    slot_block: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """q [B, 1, H, Dh] (compute dtype) against a K-MAJOR int8 cache
    ck_q/cv_q [B, K, M, Dh] with scales ck_s/cv_s [B, K, M] (f32) and a
    readable-position mask valid [B, M] (bool) → attn [B, 1, H, Dh].

    Exact w.r.t. the scale-folded XLA read (``_attend_cached`` with
    k_scale/v_scale, modulo the cache transpose) up to f32 reduction
    order — differential-tested. ``slot_block``: slots per grid step
    (must divide B); default auto-picks for VMEM fit.
    """
    b, s, h, dh = q.shape
    if s != 1:
        raise ValueError(f"decode attention is one token per slot, got S={s}")
    n_kv, m = ck_q.shape[1], ck_q.shape[2]
    rep = h // n_kv
    bb = slot_block or _pick_slot_block(b, n_kv, m, dh)
    if b % bb:
        raise ValueError(f"slot_block={bb} must divide batch={b}")
    if interpret is None:
        interpret = _default_interpret()
    qg = q[:, 0].reshape(b, n_kv, rep, dh)  # k-major head grouping
    mask3 = valid[:, None, :]  # [B, 1, M]
    kw = {} if interpret else tpu_compiler_params(("parallel",))
    out = pl.pallas_call(
        functools.partial(
            _kvattn_kmajor_kernel, inv_sqrt_dh=float(1.0 / np.sqrt(dh))
        ),
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bb, n_kv, m, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bb, n_kv, m), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, n_kv, m, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bb, n_kv, m), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, m), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bb, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
        **kw,
    )(qg, ck_q, ck_s.astype(jnp.float32), cv_q, cv_s.astype(jnp.float32),
      mask3)
    return out.reshape(b, 1, h, dh)


def int8_decode_attention(
    q: jax.Array,
    ck_q: jax.Array,
    ck_s: jax.Array,
    cv_q: jax.Array,
    cv_s: jax.Array,
    valid: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """q [B, 1, H, Dh] (compute dtype) against an int8 cache
    ck_q/cv_q [B, M, K, Dh] with scales ck_s/cv_s [B, M, K] (f32) and a
    readable-position mask valid [B, M] (bool) → attn [B, 1, H, Dh].

    Exact w.r.t. the scale-folded XLA read (``_attend_cached`` with
    k_scale/v_scale) up to f32 reduction order — differential-tested.
    """
    b, s, h, dh = q.shape
    if s != 1:
        raise ValueError(f"decode attention is one token per slot, got S={s}")
    m, n_kv = ck_q.shape[1], ck_q.shape[2]
    rep = h // n_kv
    if interpret is None:
        interpret = _default_interpret()
    qg = q[:, 0].reshape(b, n_kv, rep, dh)  # k-major head grouping
    mask3 = valid[:, None, :]  # [B, 1, M] — (1, M) trailing block dims
    kw = {} if interpret else tpu_compiler_params(("parallel",))
    out = pl.pallas_call(
        functools.partial(
            _kvattn_kernel, inv_sqrt_dh=float(1.0 / np.sqrt(dh))
        ),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, m, n_kv, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, m), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
        **kw,
    )(qg, ck_q, ck_s.astype(jnp.float32), cv_q, cv_s.astype(jnp.float32),
      mask3)
    return out.reshape(b, 1, h, dh)


# ------------------------------------------------------- paged (block-table)
# Block-table attention for the paged slot pool (torchkafka_tpu/kvcache):
# the cache is a SHARED pool of fixed-size blocks [NB, bs, K, Dh] and each
# slot maps logical positions to physical blocks through a per-slot block
# table [B, nblk] — multiple slots may map the same physical prefix blocks
# (radix-tree sharing), which is what decouples pool bytes from
# slots × max_context. Static shapes throughout, the XLA discipline: the
# write is a scatter at (table[pos // bs], pos % bs), the read a gather of
# each slot's nblk blocks into a contiguous [B, nblk·bs, K, Dh] logical
# view, masked to the live length. The gather materialises the per-slot
# view each call (read bytes match the dense pool read); the wins are
# STORAGE (shared prefixes held once; pool sized to live tokens, not
# slots × max_len) and PREFILL compute (cached prefixes skip re-prefill).


def paged_gather(pool: jax.Array, table: jax.Array) -> jax.Array:
    """Gather a per-slot logical cache view from a block pool.

    pool: [NB, bs, ...rest]; table: [B, nblk] int32 physical block ids →
    [B, nblk * bs, ...rest] — logical position p of slot b lands at
    index p (block table order), so position masks apply unchanged."""
    b, nblk = table.shape
    return pool[table].reshape(b, nblk * pool.shape[1], *pool.shape[2:])


def paged_scatter(
    pool: jax.Array, table: jax.Array, positions: jax.Array,
    values: jax.Array,
) -> jax.Array:
    """Write ``values`` [B, S, ...rest] at logical ``positions`` [B, S]
    through ``table`` [B, nblk] into ``pool`` [NB, bs, ...rest].

    Live slots write only blocks they own privately (sharing is limited
    to whole blocks strictly below any written position — the radix
    contract), so no two live slots ever collide. Idle slots' table rows
    point every entry at the sink block (kvcache.SINK_BLOCK), which no
    live table references — their unconditional frozen-position writes
    land there harmlessly (masking the write would cost a pool-sized
    select per layer; serve._slot_layer_step's lesson)."""
    bs = pool.shape[1]
    blk = jnp.take_along_axis(table, positions // bs, axis=1)  # [B, S]
    off = positions % bs
    return pool.at[blk, off].set(values.astype(pool.dtype))


def paged_gather_kmajor(pool: jax.Array, table: jax.Array) -> jax.Array:
    """``paged_gather`` for K-MAJOR-PER-BLOCK pools.

    pool: [NB, K, bs, ...rest] (payload rest=(Dh,), scales rest=());
    table: [B, nblk] int32 → [B, nblk * bs, K, ...rest]. The int8 paged
    pool stores each block K-major so the Pallas block-table kernel's
    per-block tiles are the v3 kernel's [K, bs, Dh] shape (one batched
    dot over (slot, head), no per-head relayout); the XLA read pays one
    transpose of the gathered view to recover the logical
    [B, M', K, ...] layout ``_attend_cached`` expects."""
    b, nblk = table.shape
    v = jnp.swapaxes(pool[table], 2, 3)  # [B, nblk, bs, K, ...rest]
    return v.reshape(b, nblk * pool.shape[2], *v.shape[3:])


def paged_scatter_kmajor(
    pool: jax.Array, table: jax.Array, positions: jax.Array,
    values: jax.Array,
) -> jax.Array:
    """``paged_scatter`` for K-major-per-block pools: ``values``
    [B, S, K, ...rest] written at logical ``positions`` [B, S] through
    ``table`` into ``pool`` [NB, K, bs, ...rest]. Same ownership rules
    as ``paged_scatter`` (sink-routed idle writes, private-block-only
    live writes)."""
    bs = pool.shape[2]
    blk = jnp.take_along_axis(table, positions // bs, axis=1)  # [B, S]
    off = positions % bs
    # Advanced indices separated by the K slice broadcast to the front:
    # pool[blk, :, off] is [B, S, K, ...rest], matching ``values``.
    return pool.at[blk, :, off].set(values.astype(pool.dtype))


def block_table_attention_q8(
    x, q, k_new, v_new, pool_kq, pool_ks, pool_vq, pool_vs, table,
    positions, layer, cfg,
):
    """``block_table_attention`` over the INT8 paged pool: fresh k/v are
    quantized into the shared group-wise scheme (``models.quant.
    quant_kv_groups`` — one absmax scale per (position, head), the same
    groups the dense int8 slot pool stores, which is what makes
    int8-paged serving token-exact vs int8-DENSE serving), scattered
    K-major-per-block (payload [NB, K, bs, Dh] + scales [NB, K, bs]),
    and read back through the scale-folded ``_attend_cached`` on the
    gathered logical view. Returns (x, pool_kq, pool_ks, pool_vq,
    pool_vs). The Pallas block-table kernel replaces only this READ on
    the decode path (``int8_paged_decode_attention``); the write
    half is shared."""
    from torchkafka_tpu.models.generate import _attend_cached
    from torchkafka_tpu.models.quant import quant_kv_groups

    kq, ks = quant_kv_groups(k_new)  # [B, S, K, Dh] int8, [B, S, K] f32
    vq, vs = quant_kv_groups(v_new)
    pool_kq = paged_scatter_kmajor(pool_kq, table, positions, kq)
    pool_ks = paged_scatter_kmajor(pool_ks, table, positions, ks)
    pool_vq = paged_scatter_kmajor(pool_vq, table, positions, vq)
    pool_vs = paged_scatter_kmajor(pool_vs, table, positions, vs)
    ck = paged_gather_kmajor(pool_kq, table)  # [B, M', K, Dh] int8
    cv = paged_gather_kmajor(pool_vq, table)
    cks = paged_gather_kmajor(pool_ks, table)  # [B, M', K] f32
    cvs = paged_gather_kmajor(pool_vs, table)
    valid = (
        jnp.arange(ck.shape[1])[None, None, :] <= positions[:, :, None]
    )  # [B, S, M']
    x = _attend_cached(
        x, q, ck, cv, valid, layer, cfg, k_scale=cks, v_scale=cvs
    )
    return x, pool_kq, pool_ks, pool_vq, pool_vs


def block_table_attention(
    x, q, k_new, v_new, pool_k, pool_v, table, positions, layer, cfg,
):
    """One layer of write-then-attend over a paged pool.

    x: [B, S, D]; q/k_new/v_new: [B, S, ·, Dh] (already rope'd);
    pools: [NB, bs, K, Dh]; table: [B, nblk]; positions: [B, S] the
    logical positions of the S queries. Writes k/v at ``positions``
    (write-before-attend, the serving discipline), gathers each slot's
    logical view, masks per query to [0, positions[b, s]] and runs the
    shared ``_attend_cached`` tail — the SAME math as the dense slot
    pool on a gathered operand, so paged serving stays token-comparable
    with the dense path. Returns (x, pool_k, pool_v)."""
    from torchkafka_tpu.models.generate import _attend_cached

    pool_k = paged_scatter(pool_k, table, positions, k_new)
    pool_v = paged_scatter(pool_v, table, positions, v_new)
    ck = paged_gather(pool_k, table)  # [B, M', K, Dh]
    cv = paged_gather(pool_v, table)
    valid = (
        jnp.arange(ck.shape[1])[None, None, :] <= positions[:, :, None]
    )  # [B, S, M'] per-query masks, live-length bounded
    x = _attend_cached(x, q, ck, cv, valid, layer, cfg)
    return x, pool_k, pool_v


# ------------------------------------------------------------------ v3
# Dynamic-length read: the capability XLA's static shapes cannot express.
# Every XLA spelling of decode attention (and kernels v1/v2) reads the
# FULL pool and discards masked positions; per-slot fills vary in
# continuous batching, so the discarded bytes are real HBM traffic. v3
# takes the per-slot watermark as a SCALAR-PREFETCH argument, keeps the
# pool in HBM (memory_space=ANY), and manually DMAs M-blocks with double
# buffering, running the per-block online-softmax (flash) recurrence —
# the fori_loop bound is ceil((pos+1)/mb), so blocks beyond a slot's
# fill are never fetched.


def _kvattn_dynlen_kernel(
    pos_ref, q_ref, kq_hbm, ks_hbm, vq_hbm, vs_hbm, o_ref,
    kt, st, vt, wt, sems, *, mb: int, inv_sqrt_dh: float,
):
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    pos = pos_ref[b]
    n_blocks = (pos + mb) // mb  # ceil((pos + 1) / mb), pos >= 0
    q = q_ref[0]  # [K, rep, Dh] compute dtype
    n_kv, rep, dh = q.shape

    # CROSS-PROGRAM PREFETCH. Grid programs run sequentially (semantics
    # "arbitrary") and scratch persists across them, so each program's
    # FIRST block is DMA'd by its predecessor during that predecessor's
    # last-block compute — without this, every slot begins with a DMA
    # stall (measured +24% at full fill vs v2's automatic pipeline).
    # Buffer parity must therefore be GLOBAL over the whole run, not
    # per-program: block (slot, j) uses parity (prefix_blocks(slot) + j)
    # % 2, computable by any program from the prefetched watermarks.
    def blocks_of(t):
        return (pos_ref[t] + mb) // mb

    parity0 = jax.lax.fori_loop(
        0, b, lambda t, acc: acc + blocks_of(t), jnp.int32(0)
    ) % 2

    def dmas(slot, row, j):
        return (
            pltpu.make_async_copy(
                kq_hbm.at[row, :, pl.ds(j * mb, mb), :], kt.at[slot],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                ks_hbm.at[row, :, pl.ds(j * mb, mb)], st.at[slot],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                vq_hbm.at[row, :, pl.ds(j * mb, mb), :], vt.at[slot],
                sems.at[slot, 2],
            ),
            pltpu.make_async_copy(
                vs_hbm.at[row, :, pl.ds(j * mb, mb)], wt.at[slot],
                sems.at[slot, 3],
            ),
        )

    @pl.when(b == 0)
    def _():  # no predecessor: start our own first block
        for d in dmas(parity0 % 2, b, 0):
            d.start()

    m0 = jnp.full((n_kv, rep), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, rep), jnp.float32)
    a0 = jnp.zeros((n_kv, rep, dh), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        slot = (parity0 + j) % 2

        @pl.when(j + 1 < n_blocks)
        def _():
            for d in dmas((parity0 + j + 1) % 2, b, j + 1):
                d.start()

        @pl.when((j + 1 == n_blocks) & (b + 1 < nb))
        def _():  # prefetch the NEXT program's first block
            for d in dmas((parity0 + n_blocks) % 2, b + 1, 0):
                d.start()

        for d in dmas(slot, b, j):
            d.wait()
        kk = kt[slot].astype(q.dtype)  # [K, mb, Dh]
        s = jax.lax.dot_general(
            q, kk, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, rep, mb]
        s = s * st[slot][:, None, :] * inv_sqrt_dh
        col = jax.lax.broadcasted_iota(jnp.int32, (n_kv, rep, mb), 2) + j * mb
        s = jnp.where(col <= pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)  # first block: exp(-inf - m) = 0
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + jnp.sum(p, axis=-1)
        pw = (p * wt[slot][:, None, :]).astype(q.dtype)
        vv = vt[slot].astype(q.dtype)
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            pw, vv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    o_ref[0] = (acc / l[..., None]).astype(o_ref.dtype)


def dynlen_block(max_len: int) -> int:
    """Largest of (512, 256, 128, 64, 8) dividing the pool length — the
    M-block granularity of the dynamic-length read (skipping works at
    block granularity; smaller blocks skip more but issue more DMAs)."""
    for mb in (512, 256, 128, 64, 8):
        if max_len % mb == 0:
            return mb
    return 0  # no tiling → caller must fall back


def int8_decode_attention_dynlen(
    q: jax.Array,
    ck_q: jax.Array,
    ck_s: jax.Array,
    cv_q: jax.Array,
    cv_s: jax.Array,
    pos: jax.Array,
    *,
    block: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """q [B, 1, H, Dh] against a K-MAJOR int8 cache ck_q/cv_q
    [B, K, M, Dh] with scales [B, K, M] (f32), reading ONLY positions
    [0, pos[b]] per slot (pos: [B] int32 watermarks) → attn
    [B, 1, H, Dh]. HBM traffic scales with the actual fill, not the
    pool size — inexpressible in XLA, where every read is pool-shaped.

    Exact w.r.t. the scale-folded read restricted to valid positions
    (flash-style online softmax; differential-tested against v2 with
    ``valid = arange(M) <= pos[:, None]``).
    """
    b, s, h, dh = q.shape
    if s != 1:
        raise ValueError(f"decode attention is one token per slot, got S={s}")
    n_kv, m = ck_q.shape[1], ck_q.shape[2]
    rep = h // n_kv
    mb = block or dynlen_block(m)
    if not mb or m % mb:
        raise ValueError(f"block {mb} must divide pool length {m}")
    if interpret is None:
        interpret = _default_interpret()
    qg = q[:, 0].reshape(b, n_kv, rep, dh)
    # SEQUENTIAL grid ("arbitrary"): the cross-program prefetch scheme
    # relies on program i+1's first block being DMA'd by program i, so
    # the order must be the textual one.
    kw = {} if interpret else tpu_compiler_params(("arbitrary",))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_kv, rep, dh), lambda i, pos: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
        ],
        out_specs=pl.BlockSpec((1, n_kv, rep, dh), lambda i, pos: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, n_kv, mb, dh), jnp.int8),   # k tiles
            pltpu.VMEM((2, n_kv, mb), jnp.float32),    # k scales
            pltpu.VMEM((2, n_kv, mb, dh), jnp.int8),   # v tiles
            pltpu.VMEM((2, n_kv, mb), jnp.float32),    # v scales
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kvattn_dynlen_kernel, mb=mb,
            inv_sqrt_dh=float(1.0 / np.sqrt(dh)),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
        **kw,
    )(pos.astype(jnp.int32), qg, ck_q, ck_s.astype(jnp.float32), cv_q,
      cv_s.astype(jnp.float32))
    return out.reshape(b, 1, h, dh)


def _serving_shard_specs(mesh):
    """(batch_axes, tp, manual) for the sharded decode-kernel wrappers:
    slots over ``data``, kv/q heads over ``tp`` — exactly the dense slot
    pool's ``kv_sharding`` axes, so the wrapped kernel reads the pool in
    the layout serving already stores it in. ``fsdp``/``ep``/``pp`` axes
    stay out of the manual region (weight-only axes; the kernel's
    operands are replicated across them)."""
    batch_axes = tuple(a for a in ("data",) if a in mesh.shape)
    tp = "tp" if "tp" in mesh.shape else None
    manual = frozenset(batch_axes) | (frozenset({tp}) if tp else frozenset())
    return (batch_axes if batch_axes else None), tp, manual


def int8_decode_attention_dynlen_sharded(
    q: jax.Array,
    ck_q: jax.Array,
    ck_s: jax.Array,
    cv_q: jax.Array,
    cv_s: jax.Array,
    pos: jax.Array,
    mesh,
    *,
    block: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """``int8_decode_attention_dynlen`` under a serving mesh.

    A Pallas call is opaque to GSPMD (the ``flash_attention_sharded``
    lesson), but the decode read is (slot, head)-parallel with no
    collectives — each shard attends its own slots' watermarked pool
    over its own kv heads — so ``shard_map`` splits it exactly like the
    XLA read's layouts: q/pos/caches batch over ``data``, kv heads over
    ``tp``. Requirements (the capability probe gates on these): B
    divisible by data, H and K by tp."""
    from torchkafka_tpu.ops._compat import shard_map
    from jax.sharding import PartitionSpec as P

    bspec, tp, manual = _serving_shard_specs(mesh)
    qspec = P(bspec, None, tp, None)   # [B, 1, H, Dh]
    cspec = P(bspec, tp, None, None)   # [B, K, M, Dh] K-major payloads
    sspec = P(bspec, tp, None)         # [B, K, M] scales
    fn = shard_map(
        functools.partial(
            int8_decode_attention_dynlen, block=block, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(qspec, cspec, sspec, cspec, sspec, P(bspec)),
        out_specs=qspec,
        axis_names=manual,
        check_vma=False,
    )
    return fn(q, ck_q, ck_s, cv_q, cv_s, pos)


def int8_paged_decode_attention_sharded(
    q: jax.Array,
    pool_kq: jax.Array,
    pool_ks: jax.Array,
    pool_vq: jax.Array,
    pool_vs: jax.Array,
    table: jax.Array,
    pos: jax.Array,
    mesh,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """``int8_paged_decode_attention`` under a serving mesh.

    Sharded over ``tp`` ONLY — kv/q heads split per shard, the block
    pools per-block over tp (``generate.paged_pool_kmajor_sharding``'s
    per-layer slice), and slots/tables/watermarks REPLICATED across
    every other axis. That matches the paged serving program's
    invariant (serve.py ``pin_paged``): the data axis stays out of the
    paged path entirely — block pools are shared storage with no slot
    axis to split, and re-introducing data sharding at this kernel's
    boundary re-triggers the jax-0.4.x partitioned-concat miscompile
    the rest of the program avoids. Each tp shard DMAs only live
    blocks for its own heads; no collectives."""
    from torchkafka_tpu.ops._compat import shard_map
    from jax.sharding import PartitionSpec as P

    _bspec, tp, _manual = _serving_shard_specs(mesh)
    manual = frozenset({tp}) if tp else frozenset()
    if not manual:
        # No tp axis: nothing to split — the plain kernel call inside
        # the (data-replicated) paged program is already correct.
        return int8_paged_decode_attention(
            q, pool_kq, pool_ks, pool_vq, pool_vs, table, pos,
            interpret=interpret,
        )
    qspec = P(None, None, tp, None)    # [B, 1, H, Dh]
    pspec = P(None, tp, None, None)    # [NB, K, bs, Dh] payload pools
    sspec = P(None, tp, None)          # [NB, K, bs] scale pools
    fn = shard_map(
        functools.partial(int8_paged_decode_attention, interpret=interpret),
        mesh=mesh,
        in_specs=(qspec, pspec, sspec, pspec, sspec, P(None, None),
                  P(None)),
        out_specs=qspec,
        axis_names=manual,
        check_vma=False,
    )
    return fn(q, pool_kq, pool_ks, pool_vq, pool_vs, table, pos)


# ------------------------------------------------------------------ v4
# Block-table read: the v3 watermark-DMA structure extended to read
# THROUGH per-slot block tables (the int8 PAGED pool). Both the pool
# watermarks (pos) and the block tables arrive by scalar prefetch; the
# per-slot block loop DMAs exactly ceil((pos+1)/bs) physical blocks —
# ``pool_kq.at[table[b, j]]`` — so HBM traffic scales with each slot's
# live length AND the host-side indirection (which physical block backs
# which logical position) never materialises a gathered per-slot view
# the way the XLA spelling must (paged_gather copies the view every
# layer, every tick). The pool is K-MAJOR-PER-BLOCK ([NB, K, bs, Dh] /
# [NB, K, bs]) so each block tile is exactly the v3 kernel's [K, mb,
# Dh] shape: one batched dot over (slot, head), no per-head relayout
# (the v1 postmortem's rule). Cross-program first-block prefetch and
# global buffer parity are carried over from v3 verbatim — parity is
# the prefix-sum of per-slot block counts, computable by any program
# from the prefetched watermarks.


def _kvattn_paged_kernel(
    pos_ref, table_ref, q_ref, kq_hbm, ks_hbm, vq_hbm, vs_hbm, o_ref,
    kt, st, vt, wt, sems, *, bs: int, inv_sqrt_dh: float,
):
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    pos = pos_ref[b]
    n_blocks = (pos + bs) // bs  # ceil((pos + 1) / bs), pos >= 0
    q = q_ref[0]  # [K, rep, Dh] compute dtype
    n_kv, rep, dh = q.shape

    def blocks_of(t):
        return (pos_ref[t] + bs) // bs

    parity0 = jax.lax.fori_loop(
        0, b, lambda t, acc: acc + blocks_of(t), jnp.int32(0)
    ) % 2

    def dmas(slot, row, j):
        blk = table_ref[row, j]  # physical block id — the indirection
        return (
            pltpu.make_async_copy(
                kq_hbm.at[blk], kt.at[slot], sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                ks_hbm.at[blk], st.at[slot], sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                vq_hbm.at[blk], vt.at[slot], sems.at[slot, 2],
            ),
            pltpu.make_async_copy(
                vs_hbm.at[blk], wt.at[slot], sems.at[slot, 3],
            ),
        )

    @pl.when(b == 0)
    def _():  # no predecessor: start our own first block
        for d in dmas(parity0 % 2, b, 0):
            d.start()

    m0 = jnp.full((n_kv, rep), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, rep), jnp.float32)
    a0 = jnp.zeros((n_kv, rep, dh), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        slot = (parity0 + j) % 2

        @pl.when(j + 1 < n_blocks)
        def _():
            for d in dmas((parity0 + j + 1) % 2, b, j + 1):
                d.start()

        @pl.when((j + 1 == n_blocks) & (b + 1 < nb))
        def _():  # prefetch the NEXT program's first block
            for d in dmas((parity0 + n_blocks) % 2, b + 1, 0):
                d.start()

        for d in dmas(slot, b, j):
            d.wait()
        kk = kt[slot].astype(q.dtype)  # [K, bs, Dh]
        s = jax.lax.dot_general(
            q, kk, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, rep, bs]
        s = s * st[slot][:, None, :] * inv_sqrt_dh
        col = jax.lax.broadcasted_iota(jnp.int32, (n_kv, rep, bs), 2) + j * bs
        s = jnp.where(col <= pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)  # first block: exp(-inf - m) = 0
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + jnp.sum(p, axis=-1)
        pw = (p * wt[slot][:, None, :]).astype(q.dtype)
        vv = vt[slot].astype(q.dtype)
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            pw, vv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    o_ref[0] = (acc / l[..., None]).astype(o_ref.dtype)


def paged_kernel_applicable(head_dim: int, block_size: int) -> bool:
    """Compiled-Mosaic tiling constraints for the block-table read:
    lane-aligned head_dim and sublane-aligned block size (the [K, bs]
    scale tiles need bs % 8; Dh is the lane dim of the payload tiles).
    Interpret mode accepts anything; tests force it. Callers should
    additionally require a reasonable block size (>= 256) on TPU —
    skipping works at block granularity, but tiny blocks drown in
    per-block DMA/recurrence overhead (the dynlen_block lesson)."""
    return head_dim % 128 == 0 and block_size % 8 == 0


def int8_paged_decode_attention(
    q: jax.Array,
    pool_kq: jax.Array,
    pool_ks: jax.Array,
    pool_vq: jax.Array,
    pool_vs: jax.Array,
    table: jax.Array,
    pos: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """q [B, 1, H, Dh] against the int8 PAGED pool — K-major-per-block
    payloads pool_kq/pool_vq [NB, K, bs, Dh] with scales pool_ks/pool_vs
    [NB, K, bs] (f32) — read through per-slot block tables ``table``
    [B, nblk] (int32) at per-slot watermarks ``pos`` [B] (positions
    [0, pos[b]] readable) → attn [B, 1, H, Dh].

    Only ceil((pos+1)/bs) physical blocks are DMA'd per slot, each by
    table indirection, so HBM traffic scales with live tokens and no
    gathered per-slot view is ever materialised (the XLA block-table
    read copies one per layer per tick). Exact w.r.t. the scale-folded
    gathered read restricted to valid positions (flash-style online
    softmax; differential-tested against ``paged_gather_kmajor`` +
    ``_attend_cached``)."""
    b, s, h, dh = q.shape
    if s != 1:
        raise ValueError(f"decode attention is one query per slot, got S={s}")
    n_kv, bs = pool_kq.shape[1], pool_kq.shape[2]
    rep = h // n_kv
    if interpret is None:
        interpret = _default_interpret()
    qg = q[:, 0].reshape(b, n_kv, rep, dh)
    # SEQUENTIAL grid ("arbitrary"): cross-program prefetch, as v3.
    kw = {} if interpret else tpu_compiler_params(("arbitrary",))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # watermarks AND block tables
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_kv, rep, dh), lambda i, pos, tbl: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
            pl.BlockSpec(memory_space=_ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, n_kv, rep, dh), lambda i, pos, tbl: (i, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, n_kv, bs, dh), jnp.int8),   # k tiles
            pltpu.VMEM((2, n_kv, bs), jnp.float32),    # k scales
            pltpu.VMEM((2, n_kv, bs, dh), jnp.int8),   # v tiles
            pltpu.VMEM((2, n_kv, bs), jnp.float32),    # v scales
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kvattn_paged_kernel, bs=bs,
            inv_sqrt_dh=float(1.0 / np.sqrt(dh)),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
        **kw,
    )(pos.astype(jnp.int32), table.astype(jnp.int32), qg, pool_kq,
      pool_ks.astype(jnp.float32), pool_vq, pool_vs.astype(jnp.float32))
    return out.reshape(b, 1, h, dh)

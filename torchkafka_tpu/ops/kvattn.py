"""Pallas int8-KV decode attention — EXPERIMENTAL, measured SLOWER than
the XLA scale-folded read on v5e; kept as the tested scaffold for a
DMA-pipelined successor, off by default.

The hypothesis this kernel tested (PERF.md, int8-KV section): the XLA
spelling of the int8-KV attention read materialises an int8→bf16
converted copy of the cache instead of fusing the convert into the dot's
HBM read, costing ~20% equal-slot throughput vs a bf16 cache — so a
kernel that streams int8 tiles HBM→VMEM directly (the in-VMEM convert is
on-core work) should win the bytes back. MEASURED RESULT (8B int8
weights, 96 slots, 192-token budget): this kernel runs the tick at
85.1 ms vs the XLA read's 46.8 ms — 1.8× SLOWER. Why: decode attention
is batched GEMV — the per-(slot, head) [rep≤4, Dh]×[Dh, M] dots occupy
~3% of the MXU's rows, and the (B,)-grid's one-small-DMA-per-slot
structure pipelines poorly, so the saved HBM bytes are swamped by
serialized on-core work. The fix is a redesign (M-blocked grid with
overlapped DMA and head-packed dots), not a tweak — recorded so the next
attempt starts there. Correctness is pinned by a differential test
against the scale-folded XLA read (exact to f32 reduction order).

Grid: (B,) — every slot's program is independent
(``dimension_semantics=("parallel",)``); Mosaic's block rules shape the
layout: the [B, M, K, Dh] cache blocks as (1, M, K, Dh) (the trailing
(K, Dh) pair must match the array dims), and its batched-dot positional
constraint forces the per-head static loop in the body.

Net-new vs the reference (no kernels in its tree, SURVEY.md §2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from torchkafka_tpu.ops.flash import _default_interpret

_NEG_INF = -1e30


def _kvattn_kernel(
    q_ref, kq_ref, ks_ref, vq_ref, vs_ref, mask_ref, o_ref, *,
    inv_sqrt_dh: float,
):
    q = q_ref[0]  # [K, rep, Dh] compute dtype
    # int8 tiles were DMA'd into VMEM at 1 byte/element — the convert
    # below is on-core work, not HBM traffic (the thing the kernel
    # exists to halve).
    kq = kq_ref[0].astype(q.dtype)  # [M, K, Dh]
    vq = vq_ref[0].astype(q.dtype)
    ks = ks_ref[0]  # [M, K] f32
    vs = vs_ref[0]
    mask = mask_ref[0, 0][None, :]  # [1, M]
    # STATIC loop over kv heads (K is small — 8 at the 8B shapes):
    # Mosaic's batched dot requires equal batch-dim positions, which the
    # [M, K, Dh] cache layout doesn't give; per-head 2-D dots sidestep it
    # and unroll fully at trace time.
    outs = []
    for k in range(q.shape[0]):
        s = jax.lax.dot_general(
            q[k], kq[:, k, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rep, M]
        s = s * ks[:, k][None, :] * inv_sqrt_dh
        s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        pw = (p * vs[:, k][None, :]).astype(q.dtype)
        outs.append(jax.lax.dot_general(
            pw, vq[:, k, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ))  # [rep, Dh]
    o_ref[0] = jnp.stack(outs).astype(o_ref.dtype)


def kernel_applicable(head_dim: int, max_len: int) -> bool:
    """Compiled-Mosaic tiling constraints: lane-aligned head_dim and
    sublane-aligned pool length (the (M, K)-trailing scale blocks need
    M % 8; Dh is the lane dim of the payload blocks). Interpret mode
    accepts anything; tests force it."""
    return head_dim % 128 == 0 and max_len % 8 == 0


def int8_decode_attention(
    q: jax.Array,
    ck_q: jax.Array,
    ck_s: jax.Array,
    cv_q: jax.Array,
    cv_s: jax.Array,
    valid: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """q [B, 1, H, Dh] (compute dtype) against an int8 cache
    ck_q/cv_q [B, M, K, Dh] with scales ck_s/cv_s [B, M, K] (f32) and a
    readable-position mask valid [B, M] (bool) → attn [B, 1, H, Dh].

    Exact w.r.t. the scale-folded XLA read (``_attend_cached`` with
    k_scale/v_scale) up to f32 reduction order — differential-tested.
    """
    b, s, h, dh = q.shape
    if s != 1:
        raise ValueError(f"decode attention is one token per slot, got S={s}")
    m, n_kv = ck_q.shape[1], ck_q.shape[2]
    rep = h // n_kv
    if interpret is None:
        interpret = _default_interpret()
    qg = q[:, 0].reshape(b, n_kv, rep, dh)  # k-major head grouping
    mask3 = valid[:, None, :]  # [B, 1, M] — (1, M) trailing block dims
    kw = {}
    if pltpu is not None and not interpret:
        params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kw["compiler_params"] = params_cls(
            dimension_semantics=("parallel",)
        )
    out = pl.pallas_call(
        functools.partial(
            _kvattn_kernel, inv_sqrt_dh=float(1.0 / np.sqrt(dh))
        ),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, m, n_kv, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, m, n_kv), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, m), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_kv, rep, dh), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
        **kw,
    )(qg, ck_q, ck_s.astype(jnp.float32), cv_q, cv_s.astype(jnp.float32),
      mask3)
    return out.reshape(b, 1, h, dh)

"""Attention: dense (XLA), ring, and Ulysses (sequence-parallel) implementations.

Net-new vs the reference (SURVEY.md §2: no attention anywhere in its tree);
built TPU-first:

- ``mha``: one fused einsum-softmax-einsum chain. XLA fuses the mask/softmax
  elementwise work into the two MXU matmuls; for moderate sequence lengths
  this is the fastest thing you can write without a custom kernel.
- ``ring_attention``: blockwise attention with online softmax over a
  sequence-parallel mesh axis. Each device holds a [B, S/n, H, D] shard of
  q/k/v; k/v shards rotate around the ring via ``lax.ppermute`` (ICI
  neighbour hops — the cheapest collective on a TPU torus) while every
  device's q stays resident. Memory per device is O(S/n), enabling contexts
  n× longer than a single chip's HBM would allow. Numerics follow the
  flash-attention online-softmax recurrence (running max m, running
  normalizer l) so the result is exact, not approximate.
- ``ulysses_attention``: the all-to-all alternative — two ``lax.all_to_all``
  exchanges convert the sequence split into a head split and back, so each
  device runs one full-sequence flash call over H/n heads. Same exact
  result, different comm/compute shape (see its docstring for the
  ring-vs-ulysses tradeoff).

Both are differentiable (``ppermute`` and ``lax.scan`` have transpose rules),
so ring attention composes with ``jax.value_and_grad`` in the training step.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from torchkafka_tpu.ops._compat import shard_map  # noqa: E402

_NEG_INF = -1e30  # finite sentinel: avoids -inf - -inf = nan in the recurrence


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Dense multi-head attention.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]  →  [B, Sq, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first row of
    each block — this is what lets the same kernel serve both the single-chip
    path (offsets 0) and one block step of ring attention (shard offsets).
    """
    dim = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / math.sqrt(dim))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(v.dtype)


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    use_flash: bool | None = None,
) -> jax.Array:
    """Per-device body (runs under shard_map). q/k/v: local [B, Sl, H, D].

    Dispatch: on TPU, when the local shard tiles (Sl a multiple of a flash
    block), each ring step runs the Pallas flash kernels — O(Sl·D)
    VMEM-tile memory and MXU-rate matmuls, forward AND backward (custom
    VJP below). Elsewhere (and for ragged shards) the dense blockwise body
    runs: it materialises the local [B, H, Sl, Sl] score tile per step but
    is exact and compiled XLA — far faster than interpret-mode kernels on
    CPU/GPU. ``use_flash=True`` forces the kernel path (tests exercise it
    in interpret mode); ``False`` forces dense.
    """
    from torchkafka_tpu.ops.flash import _auto_block

    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    block = _auto_block(q.shape[1])
    if use_flash and block:
        return _ring_flash(q, k, v, axis_name, axis_size, causal, block)
    return _ring_dense_local(
        q, k, v, axis_name=axis_name, axis_size=axis_size, causal=causal
    )


def _ring_dense_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
) -> jax.Array:
    batch, s_local, heads, dim = q.shape
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(dim)
    q_pos = my_idx * s_local + jnp.arange(s_local)  # global positions, [Sl]

    def block_step(carry, step):
        out, m, l, k_cur, v_cur = carry
        # Which shard k_cur holds now: it started at (my_idx + step) ... each
        # hop moves shard j's data to device j+1, so after `step` hops device
        # my_idx holds the shard originally on device (my_idx - step).
        src = (my_idx - step) % axis_size
        k_pos = src * s_local + jnp.arange(s_local)
        # Inputs stay in their compute dtype (bf16 on the MXU); accumulation
        # is f32 via preferred_element_type — flash-kernel numerics at
        # native matmul speed (f32 inputs run the MXU in multi-pass mode).
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))  # [B,H,Sq]
        p = jnp.exp(scores - m_new[..., None])  # [B,H,Sq,Sk]
        corr = jnp.exp(m - m_new)  # [B,H,Sq]
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        out_new = out * corr.transpose(0, 2, 1)[..., None] + pv
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (out_new, m_new, l_new, k_nxt, v_nxt), None

    out0 = jnp.zeros((batch, s_local, heads, dim), jnp.float32)
    m0 = jnp.full((batch, heads, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, heads, s_local), jnp.float32)
    (out, _, l, _, _), _ = lax.scan(
        block_step, (out0, m0, l0, k, v), jnp.arange(axis_size)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (out / denom).astype(v.dtype)


# ------------------------------------------------- ring over flash kernels


def _ring_perm(x, axis_name: str, axis_size: int):
    return lax.ppermute(
        x, axis_name, [(j, (j + 1) % axis_size) for j in range(axis_size)]
    )


def _ring_flash_run(q, k, v, axis_name, axis_size, causal, block):
    """Forward scan: one flash-kernel call per ring step, partial results
    merged with the standard two-softmax combine
    (lse_new = logaddexp; o weighted by exp(lse − lse_new)).
    Returns (o [BH, Sl, D] f32, lse [BH, Sl, 1] f32)."""
    from torchkafka_tpu.ops.flash import _default_interpret, _flash_fwd_bhsd, _to_bhsd

    b, sl, h, d = q.shape
    # Non-causal steps ignore the shard offsets entirely (no position mask,
    # no block-skip predicate), so the axis_index that feeds them would be a
    # dead PartitionId op — which jax 0.4.x's SPMD partitioner rejects once
    # DCE strands it outside the manual region. Skip it: offsets are only
    # meaningful under the causal mask.
    my = lax.axis_index(axis_name) if causal else 0
    interpret = _default_interpret()
    qb, kb, vb = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)

    def step(carry, t):
        o, lse, k_cur, v_cur = carry
        src = (my - t) % axis_size  # shard k_cur holds after t hops
        o_p, lse_p = _flash_fwd_bhsd(
            qb, k_cur, v_cur, causal=causal, block_q=block, block_k=block,
            interpret=interpret, q_offset=my * sl, k_offset=src * sl,
        )
        lse_new = jnp.logaddexp(lse, lse_p)
        o = (
            jnp.exp(lse - lse_new) * o
            + jnp.exp(lse_p - lse_new) * o_p.astype(jnp.float32)
        )
        return (
            o, lse_new,
            _ring_perm(k_cur, axis_name, axis_size),
            _ring_perm(v_cur, axis_name, axis_size),
        ), None

    o0 = jnp.zeros((b * h, sl, d), jnp.float32)
    lse0 = jnp.full((b * h, sl, 1), _NEG_INF, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, kb, vb), jnp.arange(axis_size))
    return o, lse


def _from_bhsd(x, b, h, dtype):
    from torchkafka_tpu.ops.flash import _from_bhsd as _fb

    return _fb(x, b, h).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, axis_size, causal, block):
    b, _, h, _ = q.shape
    o, _ = _ring_flash_run(q, k, v, axis_name, axis_size, causal, block)
    return _from_bhsd(o, b, h, v.dtype)


def _ring_flash_fwd(q, k, v, axis_name, axis_size, causal, block):
    b, _, h, _ = q.shape
    o, lse = _ring_flash_run(q, k, v, axis_name, axis_size, causal, block)
    return _from_bhsd(o, b, h, v.dtype), (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, axis_size, causal, block, res, g):
    """Ring backward: dq accumulates locally; dk/dv accumulators travel WITH
    their k/v shard (contributions are added by whichever device currently
    holds the shard) and arrive home after the full cycle of hops."""
    from torchkafka_tpu.ops.flash import _default_interpret, _flash_bwd_bhsd, _to_bhsd

    q, k, v, o, lse = res
    b, sl, h, d = q.shape
    # Same dead-PartitionId guard as _ring_flash_run: the dq/dkv kernels
    # read the offsets only under the causal mask.
    my = lax.axis_index(axis_name) if causal else 0
    interpret = _default_interpret()
    qb, kb, vb, gb = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v), _to_bhsd(g)

    def step(carry, t):
        dq, dk_cur, dv_cur, k_cur, v_cur = carry
        src = (my - t) % axis_size
        dq_p, dk_p, dv_p = _flash_bwd_bhsd(
            qb, k_cur, v_cur, o, lse, gb,
            causal=causal, block_q=block, block_k=block, interpret=interpret,
            q_offset=my * sl, k_offset=src * sl,
        )
        dq = dq + dq_p.astype(jnp.float32)
        dk_cur = dk_cur + dk_p.astype(jnp.float32)
        dv_cur = dv_cur + dv_p.astype(jnp.float32)
        return (
            dq,
            _ring_perm(dk_cur, axis_name, axis_size),
            _ring_perm(dv_cur, axis_name, axis_size),
            _ring_perm(k_cur, axis_name, axis_size),
            _ring_perm(v_cur, axis_name, axis_size),
        ), None

    zeros = jnp.zeros((b * h, sl, d), jnp.float32)
    (dq, dk, dv, _, _), _ = lax.scan(
        step, (zeros, zeros, zeros, kb, vb), jnp.arange(axis_size)
    )
    return (
        _from_bhsd(dq, b, h, q.dtype),
        _from_bhsd(dk, b, h, k.dtype),
        _from_bhsd(dv, b, h, v.dtype),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# ------------------------------------------------- Ulysses (all-to-all) SP


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    use_flash: bool | None,
) -> jax.Array:
    """Per-device body (runs under shard_map). q/k/v: local [B, Sl, H, D].

    Two all-to-alls re-partition the problem: the first trades the sequence
    split for a head split ([B, Sl, H, D] → [B, S, H/n, D]), so each device
    runs FULL-sequence attention over its head subset — one flash kernel
    call instead of a ring of n — and the second trades back. Both
    all-to-alls move the same volume a ring moves in total, but as two
    dense exchanges XLA schedules across ICI instead of n dependent
    neighbour hops; ``lax.all_to_all`` has a transpose rule, so the
    backward differentiates through the same pattern reversed.
    """
    from torchkafka_tpu.ops.flash import _auto_block, flash_attention

    a2a = functools.partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    qh = a2a(q, split_axis=2, concat_axis=1)  # [B, S, Hq/n, D]
    kh = a2a(k, split_axis=2, concat_axis=1)  # [B, S, Hkv/n, D]
    vh = a2a(v, split_axis=2, concat_axis=1)
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash and _auto_block(qh.shape[1]):
        out = flash_attention(qh, kh, vh, causal)  # GQA-native kv reads
    else:
        from torchkafka_tpu.ops.flash import _repeat_kv

        kh, vh = _repeat_kv(qh, kh, vh)  # dense path: repeat kv for GQA
        out = mha(qh, kh, vh, causal=causal)
    return a2a(out, split_axis=1, concat_axis=2)  # back to [B, Sl, H, D]


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    use_flash: bool | None = None,
) -> jax.Array:
    """Exact sequence-parallel attention via all-to-all head re-partitioning
    (the DeepSpeed-Ulysses pattern, built from ``lax.all_to_all`` over the
    mesh axis rather than any NCCL analog).

    Same contract as ``ring_attention`` — global [B, S, H, D] arrays,
    seq-sharded over ``axis_name`` — but a different comm/compute shape:
    2 all-to-alls bracketing ONE full-sequence attention per device,
    versus n dependent ppermute hops each bracketing a shard-sized
    attention. Ulysses needs head counts divisible by the axis size
    (heads are the re-partition currency); ring has no head constraint
    and GQA kv travels unrepeated. Pick per model: many-headed dense
    models → ulysses; few-kv-head GQA at extreme context → ring.
    """
    axis_size = mesh.shape[axis_name]
    if axis_size == 1:
        return mha(q, k, v, causal=causal) if q.shape[2] == k.shape[2] else (
            _gqa_dense(q, k, v, causal)
        )
    if q.shape[2] % axis_size or k.shape[2] % axis_size:
        raise ValueError(
            f"ulysses_attention re-partitions heads over {axis_name!r} "
            f"(size {axis_size}): q heads {q.shape[2]} and kv heads "
            f"{k.shape[2]} must both be divisible by it — use "
            "ring_attention for indivisible head counts"
        )
    from torchkafka_tpu.ops._compat import axis_is_manual

    body = functools.partial(
        _ulysses_local, axis_name=axis_name, axis_size=axis_size,
        causal=causal, use_flash=use_flash,
    )
    if axis_is_manual(axis_name):
        return body(q, k, v)
    spec = P(None, axis_name, None, None)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )(q, k, v)


def _gqa_dense(q, k, v, causal):
    from torchkafka_tpu.ops.flash import _repeat_kv

    k, v = _repeat_kv(q, k, v)
    return mha(q, k, v, causal=causal)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axes: tuple[str, ...] | str | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """Exact sequence-parallel attention over ``mesh[axis_name]``.

    q/k/v are *global* [B, S, H, D] arrays (inside jit, sharded along S over
    ``axis_name`` and along B over ``batch_axes``); the shard_map body sees
    the local shards and exchanges k/v around the ring. ``use_flash``:
    None = Pallas flash kernels per ring step on TPU, dense XLA elsewhere;
    True/False forces.
    """
    if q.shape[2] != k.shape[2]:
        raise ValueError(
            "ring_attention requires equal q/kv head counts — repeat kv "
            "heads before the ring (GQA-native reads are a flash_attention "
            "feature; the ring rotates whatever kv it is given)"
        )
    axis_size = mesh.shape[axis_name]
    if axis_size == 1:
        return mha(q, k, v, causal=causal)
    from torchkafka_tpu.ops._compat import axis_is_manual

    if axis_is_manual(axis_name):
        # Already inside a manual region over axis_name (e.g. a pipeline
        # stage that bound 'sp' alongside 'pp'): q/k/v are local shards and
        # the collectives can run directly — nesting a second shard_map on
        # the same axis is illegal.
        return _ring_attention_local(
            q, k, v, axis_name=axis_name, axis_size=axis_size, causal=causal,
            use_flash=use_flash,
        )
    # Partial-manual shard_map: only the sequence axis is manual here; batch
    # (data/fsdp) sharding stays automatic, so the specs mention ONLY
    # axis_name.
    spec = P(None, axis_name, None, None)
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, axis_size=axis_size,
        causal=causal, use_flash=use_flash,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )(q, k, v)

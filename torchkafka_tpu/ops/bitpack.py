"""Device-side unpack for the sub-byte wire codec (native.pack_bits).

The ingest pipeline's scarce resource is host→device wire bytes (the
fixed_width ``wire_dtype`` rationale); for a vocabulary that needs ``bits``
< 16 bits, packing rows into a dense little-endian bit stream rides the
wire at bits/16 of uint16. The host packs in C (one call per chunk); this
op unpacks ON the accelerator — three gathers, a shift, and a mask, all
vectorized and fused by XLA into whatever consumes the tokens (typically
the embedding gather). TPU-native division of labour: compact bytes on the
slow link, bit twiddling where the FLOPs are free.

Layout contract (shared with native.pack_bits/packed_width): value i of a
row occupies bit positions [i·bits, (i+1)·bits) of the row's little-endian
bit stream. The 3-byte window read below clips its tail indices to the
buffer; a clipped (duplicated) byte only ever contributes bit positions
the final mask discards, so no row padding is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchkafka_tpu.native import packed_width


def unpack_bits(packed: jax.Array, bits: int, seq: int) -> jax.Array:
    """[..., W] uint8 → [..., seq] int32 (W = packed_width(seq, bits)).

    Jittable; differentiable nowhere (integer), used on the ingest path
    before the embedding gather.
    """
    if not 1 <= bits <= 16:
        raise ValueError("bits must be in [1, 16]")
    w = packed.shape[-1]
    expect = packed_width(seq, bits)
    if w != expect:
        raise ValueError(
            f"packed width {w} != packed_width({seq}, {bits}) = {expect}"
        )
    start = jnp.arange(seq, dtype=jnp.int32) * bits
    byte0 = start >> 3
    shift = start & 7
    b = packed.astype(jnp.int32)
    # 3-byte little-endian window per value; packed_width guarantees the
    # window is in bounds whenever its bits matter, and clipping the tail
    # index only ever duplicates bytes the mask below discards.
    last = w - 1
    b0 = jnp.take(b, byte0, axis=-1)
    b1 = jnp.take(b, jnp.minimum(byte0 + 1, last), axis=-1)
    b2 = jnp.take(b, jnp.minimum(byte0 + 2, last), axis=-1)
    window = b0 | (b1 << 8) | (b2 << 16)
    return (window >> shift) & ((1 << bits) - 1)

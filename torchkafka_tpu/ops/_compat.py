"""jax API compat for the ops kernels.

``shard_map``: jax >= 0.5 exposes it top-level with ``axis_names`` (the
manual axes) and ``check_vma``; jax 0.4.x only has
``jax.experimental.shard_map.shard_map`` whose equivalents are ``auto``
(the COMPLEMENT of the manual axes) and ``check_rep``. Every call site in
this package uses the new keyword spelling; this adapter translates it so
one spelling serves both jax generations instead of three modules each
binding ``jax.shard_map`` and dying at import on 0.4.x.
"""

from __future__ import annotations

import jax

# jax 0.4.x defaults jax_threefry_partitionable=False, under which a
# jit-compiled jax.random draw with a sharding constraint produces
# DIFFERENT values depending on the layout (the tp-sharded DLRM tables
# initialize to different numbers than the replicated ones — the
# test_recsys tp-gather "mismatch" was never the gather). jax >= 0.5
# defaults the flag True, where random bits are sharding-invariant by
# construction. Align the 0.4.x line with the current default so the same
# (key, shape) gives the same values on every mesh on both jax lines.
if getattr(jax, "shard_map", None) is None:  # the 0.4.x probe used below
    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # noqa: BLE001 - flag absent: nothing to align
        pass


def _shard_map_via_experimental(
    f, *, mesh, in_specs, out_specs, axis_names, check_vma=False,
):
    from jax.experimental.shard_map import shard_map as _sm

    # The faithful translation of axis_names is auto = (mesh axes -
    # axis_names), but partial-manual regions hard-ABORT XLA compile on
    # the 0.4.x CPU backend (SIGABRT, killing the process — not a
    # catchable error). Run FULL manual instead: the call sites' specs
    # mention only the manual axes, so under full manual the remaining
    # axes see replicated views — same math, redundant compute across
    # those axes. Acceptable for the 0.4.x fallback only; current jax
    # takes the partial-manual fast path above.
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma),
    )


shard_map = getattr(jax, "shard_map", None) or _shard_map_via_experimental

def axis_is_manual(name: str) -> bool:
    """True when tracing inside a shard_map manual region over ``name`` —
    the guard the ring/ulysses wrappers and RoPE positioning use to avoid
    nesting a second shard_map on a bound axis. Current jax reports this
    on the abstract mesh (``manual_axes``); 0.4.x tracks manual axes only
    in the trace-time axis env, which ``core.axis_frame`` probes."""
    if name in getattr(get_abstract_mesh(), "manual_axes", ()):
        return True
    try:  # jax 0.4.x
        from jax._src import core

        core.axis_frame(name)
        return True
    except Exception:  # noqa: BLE001 - unbound axis / API moved: not manual
        return False


try:
    from jax.sharding import get_abstract_mesh
except ImportError:  # jax 0.4.x: private module, or absent entirely
    try:
        from jax._src.mesh import get_abstract_mesh  # type: ignore
    except ImportError:
        def get_abstract_mesh():  # type: ignore
            """No abstract-mesh tracking on this jax: callers getattr()
            ``manual_axes`` with a default, so None degrades to 'not in a
            manual region' (global-view positions)."""
            return None

"""Pallas int8 weight-dequant matmul: y = x @ (q · scale).

Weight-only-quantized decode is HBM-bandwidth-bound: every step streams the
full weight set for a few rows of activations (models/quant.py rationale).
Two properties make this kernel worth having next to XLA's dequant matmul:

- **Structural int8 streaming**: int8 weight tiles feed `dot_general`
  directly (Mosaic's mixed bf16×int8 MXU path — no bf16 weight copy even
  in VMEM); XLA's `(q*scale) @ x` relies on discretionary fusion for the
  same property.
- **Better numerics**: the per-output-channel scale applies ONCE to the
  f32 accumulator (scale is constant along the contraction), where the
  XLA path rounds every dequantized element to bf16 before the MXU.

Measured honestly (PERF.md): on this box XLA DOES fuse the dequant — its
path runs at bf16-dense speed or better, and through the axon tunnel all
three paths sit at the dispatch floor — so the default serving path stays
XLA (the compiler-friendly design the build contract prescribes) and this
kernel is the opt-in. The grid MUST declare
``dimension_semantics=(parallel, parallel, arbitrary)``: without it Mosaic
assumes cross-iteration dependence and serializes the pipeline (measured
60× slower).

Net-new vs the reference (no kernels of any kind in its tree, SURVEY.md
§2); the TPU analog of the CUDA dequant-GEMM kernels weight-only-quant
serving stacks ship.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from torchkafka_tpu.ops.flash import (
    _default_interpret,
    _scratch,
    tpu_compiler_params,
)


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int, mixed: bool):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 operand straight into the MXU (Mosaic's mixed-precision dot) —
    # the weight tile is never materialized in bf16, not even in VMEM. The
    # interpreter (CPU tests) has no mixed path, so it converts first.
    xb = x_ref[...]
    qb = q_ref[...] if mixed else q_ref[...].astype(xb.dtype)
    acc_ref[...] += jax.lax.dot_general(
        xb, qb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(2) == nk - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def _auto_block_mm(d: int) -> int:
    """Like flash's _auto_block but prefers 1024 — measured fastest for
    the weight-streaming matmul (fewer grid steps, bigger DMA bursts)."""
    for b in (1024, 512, 256, 128):
        if d % b == 0:
            return b
    return 0


def _xla_fallback(x2, q, scale, dtype):
    # q·scale in f32 then ONE cast — a bf16 scale would round to 8 mantissa
    # bits before the multiply (the load_weight rule, models/quant.py).
    return (x2 @ (q * scale.astype(jnp.float32)).astype(dtype)).astype(dtype)


def quantized_matmul(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    *,
    block_m: int | None = None,
    block_k: int | None = None,
    block_n: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """x [.., K] (bf16/f32) @ int8 q [K, N] with per-column scale → [.., N].

    ``scale`` broadcasts as [1, N] (or [N]) — one scale per output channel,
    the layout ``models.quant.quantize`` produces for 2-D weights
    (contract axis 0). Shapes that don't tile (K or N not divisible by a
    128-multiple block, row count not divisible by 8) fall back to the XLA
    dequant matmul — same math, discretionary fusion.
    """
    if scale.ndim == 1:
        scale = scale[None, :]
    *lead, k = x.shape
    # Validate the operand contract up front: the Pallas path would run on
    # mismatched shapes and return silent garbage (blocks index whatever is
    # there), where a plain matmul raises.
    if q.ndim != 2 or q.shape[0] != k:
        raise ValueError(
            f"q must be [K={k}, N], got {q.shape} — quantize() with "
            "contract_axes=(0,) for 2-D weights"
        )
    n = q.shape[1]
    if scale.shape != (1, n):
        raise ValueError(
            f"scale must broadcast as [1, N={n}] (one per output channel), "
            f"got {scale.shape}"
        )
    m = int(np.prod(lead)) if lead else 1
    x2 = x.reshape(m, k)
    if interpret is None:
        interpret = _default_interpret()
    bk = _auto_block_mm(k) if block_k is None else block_k
    bn = _auto_block_mm(n) if block_n is None else block_n
    if block_m is not None:
        bm = block_m
    elif m % 8 == 0 and m <= 512:
        bm = m  # decode shapes: a handful of rows, one m-block
    else:
        bm = _auto_block_mm(m)
    ok = bool(bk and bn and bm and k % bk == 0 and n % bn == 0 and m % bm == 0)
    if not ok:
        return _xla_fallback(x2, q, scale, x.dtype).reshape(*lead, n)
    # Without parallel semantics Mosaic serializes the whole grid
    # (measured 60x slower) — m/n blocks are independent; only the k
    # (accumulation) dim carries state.
    kw = (
        {}
        if interpret
        else tpu_compiler_params(("parallel", "parallel", "arbitrary"))
    )
    out2 = pl.pallas_call(
        functools.partial(_qmm_kernel, nk=k // bk, mixed=not interpret),
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, t: (i, t)),
            pl.BlockSpec((bk, bn), lambda i, j, t: (t, j)),
            pl.BlockSpec((1, bn), lambda i, j, t: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, t: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=_scratch([(bm, bn)]),
        interpret=interpret,
        **kw,
    )(x2, q, scale.astype(jnp.float32))
    return out2.reshape(*lead, n)

"""Flash attention: a Pallas TPU kernel for the ingest consumers' hot op.

Net-new vs the reference (no tensor ops in its tree, SURVEY.md §2). The XLA
``mha`` in attention.py materialises the [B,H,Sq,Sk] score tensor in HBM;
this kernel never does — scores live in VMEM one (block_q × block_k) tile at
a time, combined with the online-softmax recurrence (running max m, running
normaliser l), so attention memory is O(S·D) instead of O(S²) and the two
matmuls stay hot in the MXU.

Layout: [B, S, H, D] api (matching ``mha``), computed as [B·H, S, D] with a
(batch·head, q-block, k-block) grid; the k-block axis is innermost, i.e.
sequential on TPU, and the f32 accumulators persist in VMEM scratch across
its iterations. Causal blocks strictly above the diagonal are skipped via
``pl.when`` (half the FLOPs of the naive mask for long sequences).

Training: ``flash_attention`` carries a custom VJP whose backward recomputes
attention with the XLA path — forward-pass memory wins (serving, prefill,
frozen towers) are kept; long-context *training* should use ring attention
(attention.py), whose scan is natively differentiable shard-by-shard.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU run the kernel in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

from torchkafka_tpu.ops.attention import mha

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: a k-block strictly above the q-block's last row contributes
    # nothing — skip its matmuls entirely.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _block():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[:, :1] = m_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool):
    """q,k,v: [BH, S, D] → [BH, S, D]."""
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (bh, pl.cdiv(s, block_q), pl.cdiv(s, block_k))
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
    )
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ]
        if pltpu is not None
        else [
            jax.ShapeDtypeStruct((block_q, d), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
        ]
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


def _supported(s: int, block_q: int, block_k: int) -> bool:
    return s % block_q == 0 and s % block_k == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused attention. q,k,v: [B, S, H, D] → [B, S, H, D].

    Falls back to the XLA path when the sequence does not tile (S not a
    multiple of the block sizes after clamping to S).
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_impl(q, k, v, causal, block_q, block_k, interpret):
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if not _supported(s, block_q, block_k):
        return mha(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = _flash_fwd_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    # Backward = recompute with the XLA path and differentiate it. Keeps the
    # forward's memory/fusion wins where they matter (inference, prefill);
    # memory-optimal training backward is ring attention's scan.
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: mha(q, k, v, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)

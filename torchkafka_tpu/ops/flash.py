"""Flash attention: Pallas TPU kernels for the ingest consumers' hot op.

Net-new vs the reference (no tensor ops in its tree, SURVEY.md §2). The XLA
``mha`` in attention.py materialises the [B,H,Sq,Sk] score tensor in HBM;
these kernels never do — scores live in VMEM one (block_q × block_k) tile at
a time, combined with the online-softmax recurrence (running max m, running
normaliser l), so attention memory is O(S·D) instead of O(S²) and the
matmuls stay hot in the MXU.

Layout: [B, S, H, D] api (matching ``mha``), computed as [B·H, S, D] with a
(batch·head, q-block, k-block) grid; the innermost grid axis is sequential on
TPU, and the f32 accumulators persist in VMEM scratch across its iterations.
Causal blocks strictly above the diagonal are skipped via ``pl.when`` (half
the FLOPs of the naive mask for long sequences).

Training: the custom VJP is a real flash backward (the FlashAttention-2
formulation). The forward saves only (q, k, v, o, lse) — lse is the per-row
log-sum-exp ``m + log l`` emitted by the forward kernel — and the backward
runs two Pallas kernels that recompute probabilities per tile from lse:

  delta = rowsum(dO ∘ O)                       (XLA, O(S·D))
  P  = exp(S·scale − lse)                      (per VMEM tile, never in HBM)
  dV = Pᵀ dO      dS = P ∘ (dP − delta)·scale
  dQ = dS K       dK = dSᵀ Q

so ``jax.grad`` through ``flash_attention`` allocates O(S·D), never O(S²).

Per-row vectors (lse, delta) are carried as [BH, S, 1] arrays with
(1, block_q, 1) blocks: Mosaic accepts a minor block dim equal to the array
dim, and the kernels get natural [block_q, 1] columns that broadcast against
[block_q, block_k] score tiles with no sublane↔lane relayout. (jax's own TPU
flash kernel instead replicates lse across a 128-lane minor dim — 128× the
residual bytes for the same broadcast.)
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; tests on CPU run the kernel in interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

from torchkafka_tpu.ops.attention import mha

_NEG_INF = -1e30


# ------------------------------------------------------------------ forward


def _flash_kernel(
    q_ref, k_ref, v_ref, qoff_ref, koff_ref, o_ref, lse_ref,
    acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    qoff = qoff_ref[0]  # global position of q row 0 (ring shard offset)
    koff = koff_ref[0]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: a k-block strictly above the q-block's last row contributes
    # nothing — skip its matmuls entirely. With ring offsets this also
    # skips every block of a kv shard that lies wholly in the future.
    run = (
        (koff + ki * block_k <= qoff + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(run)
    def _block():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos = qoff + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = koff + ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[:, :1] = m_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # Rows that saw no allowed key (possible for a ring block wholly in
        # the future): l == 0 → lse ≈ -1e30, o = 0; the partial-merge
        # weight exp(lse - lse_new) underflows to exactly 0.
        lse_ref[0] = m_ref[:, :1] + jnp.log(l)  # [block_q, 1]


def _scratch(shapes):
    if pltpu is not None:
        return [pltpu.VMEM(sh, jnp.float32) for sh in shapes]
    return [jax.ShapeDtypeStruct(sh, jnp.float32) for sh in shapes]


def _smem_spec():
    kw = {} if pltpu is None else {"memory_space": pltpu.SMEM}
    return pl.BlockSpec((1,), lambda b, i, j: (0,), **kw)


def _offsets(q_offset, k_offset):
    return (
        jnp.asarray(q_offset, jnp.int32).reshape(1),
        jnp.asarray(k_offset, jnp.int32).reshape(1),
    )


def _kv_index(n_q_heads: int, n_kv_heads: int):
    """Grid-row → kv array row for grouped-query attention.

    q rows are laid out [batch·H + h]; the matching kv row is
    [batch·K + h // (H/K)]. With H == K this is the identity. Computed in
    the BlockSpec index map, so the kernel reads the SMALL kv tensors
    directly — no jnp.repeat materialising H/K× the kv bytes in HBM.
    """
    if n_q_heads == n_kv_heads:
        return lambda b: b
    rep = n_q_heads // n_kv_heads
    return lambda b: (b // n_q_heads) * n_kv_heads + (b % n_q_heads) // rep


def _flash_fwd_bhsd(
    q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool,
    q_offset=0, k_offset=0, n_q_heads: int = 1, n_kv_heads: int = 1,
):
    """q: [B·H, Sq, D]; k,v: [B·K, Sk, D] → ([B·H, Sq, D], lse f32).

    ``q_offset``/``k_offset`` are the global positions of row 0 (traced i32
    scalars, SMEM) — this is what lets the same kernel serve the single-chip
    path (offsets 0) and one block step of ring attention (shard offsets),
    mirroring ``mha``'s offset contract (attention.py). K < H (GQA) is
    served by the kv index map, not by materialising repeated heads.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
    )
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    qoff, koff = _offsets(q_offset, k_offset)
    kv = _kv_index(n_q_heads, n_kv_heads)
    return pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (kv(b), j, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (kv(b), j, 0), **vmem),
            _smem_spec(),
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0), **vmem),
        ],
        scratch_shapes=_scratch([(block_q, d), (block_q, 128), (block_q, 128)]),
        interpret=interpret,
    )(q, k, v, qoff, koff)


# ----------------------------------------------------------------- backward


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qoff_ref, koff_ref,
    dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    """Grid (bh, qi, ki), ki innermost: accumulate dQ for one q block."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    qoff = qoff_ref[0]
    koff = koff_ref[0]

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (
        (koff + ki * block_k <= qoff + qi * block_q + block_q - 1)
        if causal
        else True
    )

    @pl.when(run)
    def _block():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        do = do_ref[0]  # [block_q, D]
        lse = lse_ref[0]  # [block_q, 1]
        delta = delta_ref[0]  # [block_q, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qoff + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = koff + ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, D]

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qoff_ref, koff_ref,
    dk_ref, dv_ref, dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    """Grid (bh, ki, qi), qi innermost: accumulate dK, dV for one k block."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    qoff = qoff_ref[0]
    koff = koff_ref[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (
        (qoff + qi * block_q + block_q - 1 >= koff + ki * block_k)
        if causal
        else True
    )

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [block_q, 1]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qoff + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = koff + ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        # dV += Pᵀ dO: contract the q (sublane) dim.
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, D]

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_bhsd(
    q, k, v, o, lse, do, *, causal: bool, block_q: int, block_k: int,
    interpret: bool, q_offset=0, k_offset=0, n_q_heads: int = 1,
    n_kv_heads: int = 1,
):
    """q,o,do [BH, Sq, D]; k,v [BH, Sk, D]; lse [BH, Sq, 1] →
    (dq [BH, Sq, D], dk, dv [BH, Sk, D])."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # delta = rowsum(dO ∘ O): O(S·D) elementwise — XLA fuses this fine.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [BH, Sq, 1]

    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    qoff, koff = _offsets(q_offset, k_offset)
    kv = _kv_index(n_q_heads, n_kv_heads)

    def qd(idx):
        return pl.BlockSpec((1, block_q, d), idx, **vmem)

    def kd(idx):
        return pl.BlockSpec((1, block_k, d), idx, **vmem)

    def col(idx):
        return pl.BlockSpec((1, block_q, 1), idx, **vmem)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=(bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)),
        in_specs=[
            qd(lambda b, i, j: (b, i, 0)),  # q
            kd(lambda b, i, j: (kv(b), j, 0)),  # k
            kd(lambda b, i, j: (kv(b), j, 0)),  # v
            qd(lambda b, i, j: (b, i, 0)),  # do
            col(lambda b, i, j: (b, i, 0)),  # lse
            col(lambda b, i, j: (b, i, 0)),  # delta
            _smem_spec(),
            _smem_spec(),
        ],
        out_specs=qd(lambda b, i, j: (b, i, 0)),
        scratch_shapes=_scratch([(block_q, d)]),
        interpret=interpret,
    )(q, k, v, do, lse, delta, qoff, koff)

    # dk/dv are written PER Q-HEAD (grid rows would race on a shared kv row
    # otherwise); under GQA the caller group-sums the rep partials.
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        grid=(bh, pl.cdiv(sk, block_k), pl.cdiv(sq, block_q)),
        in_specs=[
            qd(lambda b, j, i: (b, i, 0)),  # q
            kd(lambda b, j, i: (kv(b), j, 0)),  # k
            kd(lambda b, j, i: (kv(b), j, 0)),  # v
            qd(lambda b, j, i: (b, i, 0)),  # do
            col(lambda b, j, i: (b, i, 0)),  # lse
            col(lambda b, j, i: (b, i, 0)),  # delta
            _smem_spec(),
            _smem_spec(),
        ],
        out_specs=[
            kd(lambda b, j, i: (b, j, 0)),
            kd(lambda b, j, i: (b, j, 0)),
        ],
        scratch_shapes=_scratch([(block_k, d), (block_k, d)]),
        interpret=interpret,
    )(q, k, v, do, lse, delta, qoff, koff)
    return dq, dk, dv


# ------------------------------------------------------------------ public


def _supported(s: int, block_q: int, block_k: int) -> bool:
    return block_q > 0 and block_k > 0 and s % block_q == 0 and s % block_k == 0


def _auto_block(s: int) -> int:
    """Largest of (512, 256, 128) dividing S — 512 benches ~5-25x faster
    than 128 (fewer grid steps, better MXU occupancy), but any non-divisor
    would silently lose the flash path for that S entirely."""
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    return 0  # no tiling → dense fallback


def _default_interpret() -> bool:
    """Interpret mode off-TPU: the kernels run under the Pallas interpreter
    (tests on the CPU mesh); compiled Mosaic on the real chip."""
    return jax.default_backend() != "tpu"


def tpu_compiler_params(dimension_semantics: tuple) -> dict:
    """``{"compiler_params": ...}`` kwargs for a compiled-Mosaic
    pallas_call, or ``{}`` when the TPU module is unavailable. One home
    for the CompilerParams/TPUCompilerParams rename fallback (the class
    was named TPUCompilerParams before jax 0.7) — shared by the flash,
    qmatmul, and kvattn kernels."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pragma: no cover
        return {}
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return {
        "compiler_params": params_cls(dimension_semantics=dimension_semantics)
    }


def _resolve(s: int, block_q: int | None, block_k: int | None, interpret):
    block_q = _auto_block(s) if block_q is None else min(block_q, s)
    block_k = _auto_block(s) if block_k is None else min(block_k, s)
    if interpret is None:
        interpret = _default_interpret()
    return block_q, block_k, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused attention. q,k,v: [B, S, H, D] → [B, S, H, D].

    Differentiable with O(S·D) memory (flash backward). Block sizes default
    to the largest of (512, 256, 128) dividing S. Falls back to the XLA
    path — forward and backward — when the sequence does not tile (no
    candidate block divides S, e.g. S < 128 or odd sizes).
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret)


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _repeat_kv(q, k, v):
    rep = q.shape[2] // k.shape[2]
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _check_heads(q, k):
    h, kh = q.shape[2], k.shape[2]
    if h % kh:
        raise ValueError(
            f"q heads ({h}) must be a multiple of kv heads ({kh}) for GQA"
        )


def _flash_impl(q, k, v, causal, block_q, block_k, interpret):
    _check_heads(q, k)
    b, s, h, d = q.shape
    block_q, block_k, interpret = _resolve(s, block_q, block_k, interpret)
    if not _supported(s, block_q, block_k):
        kk, vv = _repeat_kv(q, k, v)
        return mha(q, kk, vv, causal=causal)
    out, _ = _flash_fwd_bhsd(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        n_q_heads=h, n_kv_heads=k.shape[2],
    )
    return _from_bhsd(out, b, h)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    _check_heads(q, k)
    b, s, h, d = q.shape
    block_q, block_k, interpret = _resolve(s, block_q, block_k, interpret)
    if not _supported(s, block_q, block_k):
        # Residuals (o=None, lse=None) route the backward to the dense vjp.
        kk, vv = _repeat_kv(q, k, v)
        return mha(q, kk, vv, causal=causal), (q, k, v, None, None)
    out, lse = _flash_fwd_bhsd(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        n_q_heads=h, n_kv_heads=k.shape[2],
    )
    return _from_bhsd(out, b, h), (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o_bhsd, lse = res
    if lse is None:  # untileable shape: dense fallback, matching the forward
        def dense(q, k, v):
            kk, vv = _repeat_kv(q, k, v)
            return mha(q, kk, vv, causal=causal)

        _, vjp = jax.vjp(dense, q, k, v)
        return vjp(g)
    b, s, h, d = q.shape
    n_kv = k.shape[2]
    block_q, block_k, interpret = _resolve(s, block_q, block_k, interpret)
    dq, dk, dv = _flash_bwd_bhsd(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v), o_bhsd, lse, _to_bhsd(g),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        n_q_heads=h, n_kv_heads=n_kv,
    )
    if n_kv != h:
        # dk/dv came back as per-q-head partials [B·H, S, D]: kv grads sum
        # over each group of H/K consecutive q heads (the transpose of the
        # kv broadcast), then land in [B, S, K, D] layout.
        rep = h // n_kv
        dk = dk.reshape(b, n_kv, rep, s, d).sum(axis=2).transpose(0, 2, 1, 3)
        dv = dv.reshape(b, n_kv, rep, s, d).sum(axis=2).transpose(0, 2, 1, 3)
    else:
        dk = _from_bhsd(dk, b, n_kv)
        dv = _from_bhsd(dv, b, n_kv)
    return _from_bhsd(dq, b, h), dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention under an auto-sharded {data, fsdp, tp} mesh.

    A Pallas call is OPAQUE to GSPMD: inside a jit with sharded operands
    the partitioner cannot split the kernel the way it splits einsums, so
    plain ``flash_attention`` on a multi-device mesh either replicates the
    work or fails to partition. But batch/head-parallel attention needs NO
    communication — each (batch-shard, head-shard) attends over its own
    full sequence independently — so this wraps the kernel in
    ``shard_map``: batch over (data, fsdp), q heads AND kv heads over tp
    (the GQA group ratio is preserved per shard). Differentiable like the
    unsharded kernel (shard_map composes with the custom VJP).

    Requirements (the caller gates on these — Transformer falls back to
    the dense path otherwise): B divisible by data·fsdp, H and K by tp.
    Per-shard sequences that don't tile fall back to dense INSIDE the
    shard, same math. Mesh axes not named here (sp/pp/ep) see the inputs
    replicated, matching what GSPMD would do.
    """
    from torchkafka_tpu.ops._compat import shard_map
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.shape)
    tp = "tp" if "tp" in mesh.shape else None
    spec = P(batch_axes if batch_axes else None, None, tp, None)
    manual = frozenset(batch_axes) | (frozenset({tp}) if tp else frozenset())
    fn = shard_map(
        functools.partial(
            flash_attention, causal=causal, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # Manual over ONLY the batch/head axes; any other mesh axes
        # (sp/pp/ep) stay auto-sharded for GSPMD to manage around the
        # kernel, matching the ring/ulysses wrappers' style.
        axis_names=manual,
        check_vma=False,
    )
    return fn(q, k, v)

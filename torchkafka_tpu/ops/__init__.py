"""TPU compute ops: attention (dense / flash / ring) and friends.

The reference has no tensor ops at all (SURVEY.md §2: TP/SP/ring-attention
ABSENT — /root/reference has no model code). These ops are net-new capability
required by the north star's model-consuming scenarios (BASELINE.md configs
4-5) and by the long-context / sequence-parallel design contract: attention is
the hot op of every downstream consumer of our ingested batches, so the
framework ships MXU-shaped implementations of it.
"""

from torchkafka_tpu.ops.attention import mha, ring_attention, ulysses_attention
from torchkafka_tpu.ops.flash import flash_attention
from torchkafka_tpu.ops.qmatmul import quantized_matmul

__all__ = [
    "flash_attention",
    "mha",
    "quantized_matmul",
    "ring_attention",
    "ulysses_attention",
]

"""Parallelism layer: meshes, shardings, collectives."""

from torchkafka_tpu.parallel.mesh import (
    batch_sharding,
    global_batch,
    make_mesh,
    process_count,
    process_index,
)

__all__ = [
    "batch_sharding",
    "global_batch",
    "make_mesh",
    "process_count",
    "process_index",
]

"""Parallelism layer: meshes, shardings, multihost wiring."""

from torchkafka_tpu.parallel.mesh import (
    batch_sharding,
    global_batch,
    make_mesh,
    process_count,
    process_index,
)
from torchkafka_tpu.parallel.multihost import (
    BarrierWatchdog,
    initialize,
    pod_consumer,
    pod_partitions,
)

__all__ = [
    "BarrierWatchdog",
    "batch_sharding",
    "global_batch",
    "initialize",
    "make_mesh",
    "pod_consumer",
    "pod_partitions",
    "process_count",
    "process_index",
]

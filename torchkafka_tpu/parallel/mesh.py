"""Device mesh helpers: the TPU-native replacement for DataLoader workers.

The reference's parallelism is N host processes each owning a consumer
(/root/reference/src/kafka_dataset.py:208-233). On TPU the parallel axis is
the *device mesh*: each host process feeds its local shard of a global
jax.Array laid out over the mesh's data axis; model axes (tp/sp/...) subshard
the rest. These helpers build meshes and assemble global arrays from
host-local NumPy batches (`jax.make_array_from_process_local_data`) so
ingest composes with any pjit-sharded step function.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh from {axis_name: size}. Default: all devices on one
    'data' axis (pure DP — the reference's only strategy, lifted to chips).

    Sizes must multiply to the device count; a single -1 axis is inferred.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known != 0:
            raise ValueError(f"cannot infer -1 axis: {n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {np.prod(sizes)} devices, have {n}")
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=names)


def batch_sharding(mesh: Mesh, data_axis: str | Sequence[str] = "data") -> NamedSharding:
    """Sharding for ingest batches: leading (batch) dim split over the data
    axis (or axes, e.g. ('data','fsdp')), all other dims replicated."""
    axes = (data_axis,) if isinstance(data_axis, str) else tuple(data_axis)
    return NamedSharding(mesh, P(axes))


def global_batch(
    host_local: Any,
    mesh: Mesh,
    data_axis: str | Sequence[str] = "data",
) -> Any:
    """Assemble a global, mesh-sharded jax.Array pytree from each host's local
    NumPy batch (the TPU equivalent of the DataLoader's worker->main queue
    crossing, SURVEY.md §2 communication table).

    Each process contributes its shard; the global leading dim is
    local_batch * process_count. Single-process: local == global, data lands
    sharded across local devices without an extra copy through one device.
    """
    sharding = batch_sharding(mesh, data_axis)
    return jax.tree_util.tree_map(
        lambda leaf: jax.make_array_from_process_local_data(sharding, np.asarray(leaf)),
        host_local,
    )


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()

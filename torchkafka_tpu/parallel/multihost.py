"""Multi-host pod wiring: distributed init, pod-aligned consumers, watchdog.

The reference scales with DataLoader worker *processes on one host*
(/root/reference/src/kafka_dataset.py:208-233); a TPU pod scales with
*host processes across machines*, one per chip group, coordinated over
ICI/DCN. This module is the boot glue:

- ``initialize()``: jax.distributed bring-up (idempotent, no-op single-host).
- ``pod_consumer()``: this host's consumer with the mesh-aligned partition
  slice — the TPU equivalent of the reference's one-consumer-per-worker
  pattern, with Kafka's group protocol replaced by static assignment aligned
  to ``jax.process_index()`` (elastic group mode remains available by
  passing ``assignment=None``).
- ``BarrierWatchdog``: failure detection for the commit barrier. The barrier
  fails *closed* (nothing commits if a host is gone — records re-deliver),
  but a collective over a dead host hangs rather than raises; the watchdog
  turns "hung longer than timeout" into an explicit action (log + optional
  process exit) so the orchestrator can restart the job instead of wedging.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Sequence

import jax

from torchkafka_tpu.commit.barrier import CommitBarrier
from torchkafka_tpu.source.assignment import partitions_for_process
from torchkafka_tpu.source.records import TopicPartition

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Bring up jax.distributed if needed. → (process_index, process_count).

    Idempotent: safe to call when already initialized or on a single host
    (where it is a no-op). Under TPU orchestrators (GKE/QR) all arguments are
    auto-detected and may be omitted.
    """
    if num_processes is not None and num_processes > 1 and jax.process_count() == 1:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:  # already initialized
            logger.debug("jax.distributed.initialize: %s", e)
    return jax.process_index(), jax.process_count()


def pod_partitions(topic: str, num_partitions: int) -> list[TopicPartition]:
    """The partition slice this host owns under mesh-aligned assignment."""
    return partitions_for_process(
        topic, num_partitions, jax.process_index(), jax.process_count()
    )


def pod_consumer(
    topic: str,
    num_partitions: int,
    group_id: str,
    *,
    transport: Callable[..., Any] | None = None,
    assignment: Sequence[TopicPartition] | str = "mesh",
    **consumer_kwargs: Any,
):
    """Build this host's consumer.

    ``assignment='mesh'`` (default): static slice via ``pod_partitions`` —
    deterministic, rebalance-free, the right choice when the host count is
    fixed by the TPU topology. ``assignment=None``: join the consumer group
    and let the broker assign (elastic, survives host replacement).
    ``transport`` defaults to the kafka-python adapter; pass
    ``functools.partial(MemoryConsumer, broker)`` for tests.
    """
    if transport is None:
        from torchkafka_tpu.source.kafka import KafkaConsumer

        transport = KafkaConsumer
    if assignment == "mesh":
        assignment = pod_partitions(topic, num_partitions)
    return transport(topic, group_id=group_id, assignment=assignment, **consumer_kwargs)


class BarrierWatchdog:
    """Wraps a CommitBarrier; fires ``on_timeout`` if one barrier call hangs.

    Default action logs CRITICAL and, when ``exit_on_timeout``, terminates
    the process with ``exit_code`` — on a pod, a restart-from-last-commit is
    strictly better than a wedged collective (nothing was committed, so no
    data is lost; the Kafka group/checkpoint resume path takes over).
    """

    def __init__(
        self,
        barrier: CommitBarrier | None = None,
        *,
        timeout_s: float = 300.0,
        first_grace_s: float | None = None,
        on_timeout: Callable[[], None] | None = None,
        exit_on_timeout: bool = False,
        exit_code: int = 42,
    ) -> None:
        self._barrier = barrier if barrier is not None else CommitBarrier()
        self._timeout_s = timeout_s
        # The FIRST barrier call legitimately includes cross-host XLA
        # compile skew (one host may compile for many minutes while its
        # peers wait at the barrier) — a steady-state timeout there would
        # exit-42 a healthy pod into a compile crash-loop. Default grace:
        # 6x the timeout, floor 1800 s.
        self._first_grace_s = (
            first_grace_s
            if first_grace_s is not None
            else max(6 * timeout_s, 1800.0)
        )
        self._first_done = False
        self._exit = exit_on_timeout
        self._exit_code = exit_code
        self._on_timeout = on_timeout
        self.timed_out = False

    def _fire(self) -> None:
        self.timed_out = True
        logger.critical(
            "commit barrier exceeded %.0fs — a pod member is likely dead; "
            "nothing was committed (fail-closed), records will re-deliver",
            self._timeout_s,
        )
        if self._on_timeout is not None:
            self._on_timeout()
        if self._exit:  # pragma: no cover - kills the test process
            os._exit(self._exit_code)

    def __call__(self, wait_for: Any = None) -> None:
        timeout = self._timeout_s if self._first_done else self._first_grace_s
        timer = threading.Timer(timeout, self._fire)
        timer.daemon = True
        timer.start()
        try:
            self._barrier(wait_for)
            self._first_done = True
        finally:
            timer.cancel()

"""Host-memory tier for cold radix-cache blocks (+ optional disk spill).

The HBM block pool holds the HOT prefix state; this module is where cold
prefixes go to survive eviction. Without it, ``radix.evict`` FREES an
unreferenced leaf — the prefix re-prefills from scratch on its next hit,
and at production tenant counts (far more distinct prefixes than pool
blocks) the tree thrashes: TRAFFIC_BENCH.json's hit-by-Zipf-rank cliff
(0.89 → 0.60) is the small-scale preview. With a tier, eviction DEMOTES
the block's KV payload to a bounded pinned-host-RAM store instead
(SGLang's RadixAttention hierarchy shape), and a radix match that walks
off the in-HBM tree PROMOTES matching tier entries back into fresh pool
blocks — so the effective prefix-cache capacity is host memory (plus an
optional disk tier behind it), not pool blocks.

Contracts, each property-tested against a brute-force reference
(tests/test_tier.py):

- **byte exactness** — a demoted payload promotes back bitwise
  identical (the tier stores copies, never views; disk round-trips
  through ``numpy`` save/load). Token-exactness of tiered serving never
  *depends* on this (a tier miss just re-prefills, the same advisory
  contract as eviction), but it is what makes a promotion and a
  re-prefill indistinguishable.
- **bounded** — RAM occupancy never exceeds ``capacity_bytes``; LRU
  victims spill to ``spill_dir`` when configured, else drop.
- **deterministic** — LRU ticks on a monotone op counter (no clocks),
  so the same put/take sequence always evicts/spills the same entries:
  the property chaos-replay differentials rest on.

Keyed by the PREFIX TOKEN BYTES (the root→node token path), not by
physical block id: a tier entry is a statement about a token prefix, and
physical ids are meaningless across demote/promote cycles.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """Host-tier policy (``StreamingGenerator``'s ``kv_tier=``).

    ``capacity_bytes``: RAM bound for demoted block payloads (KV bytes
    only; index overhead is not counted). ``spill_dir``: when set, RAM-
    LRU victims spill to one ``.npy``-concatenated file each under this
    directory instead of being dropped — the (unbounded) cold tier
    behind the warm one. A ``capacity_bytes`` of 0 with a ``spill_dir``
    is a pure disk tier."""

    capacity_bytes: int
    spill_dir: str | None = None

    def __post_init__(self) -> None:
        if self.capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0, got {self.capacity_bytes}"
            )


class _Entry:
    __slots__ = ("arrays", "nbytes", "stamp", "path")

    def __init__(self, arrays, nbytes: int, stamp: int, path=None):
        self.arrays = arrays  # tuple[np.ndarray, ...] | None (spilled)
        self.nbytes = nbytes
        self.stamp = stamp
        self.path = path  # spill file when arrays is None


class HostTier:
    """Bounded host-RAM store of demoted block payloads, LRU within,
    optional disk spill behind. One payload is the tuple of per-pool
    arrays for one block (2 arrays on compute-dtype pools, 4 on int8
    payload+scale pools) — the tier is layout-blind: it stores and
    returns exactly the bytes it was handed.

    ``put`` copies (the caller's buffers may be device-backed views);
    ``take`` POPS — a promoted prefix lives in the pool again and
    re-demotes on its next eviction, so a block's bytes are accounted
    in exactly one tier at a time."""

    def __init__(self, config: TierConfig) -> None:
        self.config = config
        self._entries: dict[bytes, _Entry] = {}
        self._clock = 0
        self.occupancy_bytes = 0  # RAM tier only (spilled bytes excluded)
        self.spilled_bytes = 0
        self.puts = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0  # dropped entirely (no spill dir)
        self.spills = 0
        self.spill_loads = 0
        self.rejected = 0  # single payload larger than the whole RAM bound
        if config.spill_dir is not None:
            os.makedirs(config.spill_dir, exist_ok=True)

    def __len__(self) -> int:
        return len(self._entries)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def contains(self, key: bytes) -> bool:
        return key in self._entries

    # ----------------------------------------------------------- spill io

    def _spill_path(self, key: bytes) -> str:
        name = hashlib.sha1(key).hexdigest() + ".npy"
        return os.path.join(self.config.spill_dir, name)

    @staticmethod
    def _write_arrays(path: str, arrays) -> None:
        with open(path, "wb") as f:
            np.save(f, np.int64(len(arrays)), allow_pickle=False)
            for a in arrays:
                np.save(f, a, allow_pickle=False)

    @staticmethod
    def _read_arrays(path: str):
        with open(path, "rb") as f:
            n = int(np.load(f, allow_pickle=False))
            return tuple(np.load(f, allow_pickle=False) for _ in range(n))

    # ---------------------------------------------------------------- api

    def put(self, key: bytes, arrays) -> None:
        """Demote one block's payload. Overwrites an existing entry for
        the same prefix (idempotent re-demotion); LRU-spills/drops until
        the RAM bound holds again."""
        arrays = tuple(np.array(a, copy=True) for a in arrays)
        nbytes = sum(a.nbytes for a in arrays)
        self.puts += 1
        old = self._entries.pop(key, None)
        if old is not None:
            self._forget(old)
        if nbytes > self.config.capacity_bytes:
            if self.config.spill_dir is not None:
                path = self._spill_path(key)
                self._write_arrays(path, arrays)
                self._entries[key] = _Entry(None, nbytes, self._tick(), path)
                self.spilled_bytes += nbytes
                self.spills += 1
            else:
                self.rejected += 1
            return
        self._entries[key] = _Entry(arrays, nbytes, self._tick())
        self.occupancy_bytes += nbytes
        self._enforce_bound()

    def take(self, key: bytes):
        """Pop and return the payload for ``key`` (promotion), or None.
        Disk-spilled entries load back transparently."""
        e = self._entries.pop(key, None)
        if e is None:
            self.misses += 1
            return None
        self.hits += 1
        if e.arrays is None:
            arrays = self._read_arrays(e.path)
            self.spill_loads += 1
            self._forget(e)
            return arrays
        self.occupancy_bytes -= e.nbytes
        return e.arrays

    def _forget(self, e: _Entry) -> None:
        if e.arrays is None:
            self.spilled_bytes -= e.nbytes
            try:
                os.unlink(e.path)
            except OSError:
                pass
        else:
            self.occupancy_bytes -= e.nbytes

    def _enforce_bound(self) -> None:
        while self.occupancy_bytes > self.config.capacity_bytes:
            victim_key = min(
                (k for k, e in self._entries.items() if e.arrays is not None),
                key=lambda k: self._entries[k].stamp,
            )
            e = self._entries[victim_key]
            self.occupancy_bytes -= e.nbytes
            if self.config.spill_dir is not None:
                path = self._spill_path(victim_key)
                self._write_arrays(path, e.arrays)
                e.arrays = None
                e.path = path
                self.spilled_bytes += e.nbytes
                self.spills += 1
            else:
                del self._entries[victim_key]
                self.evictions += 1

    def summary(self) -> dict:
        return {
            "entries": len(self._entries),
            "occupancy_bytes": self.occupancy_bytes,
            "spilled_bytes": self.spilled_bytes,
            "capacity_bytes": self.config.capacity_bytes,
            "puts": self.puts,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "spills": self.spills,
            "spill_loads": self.spill_loads,
            "rejected": self.rejected,
        }

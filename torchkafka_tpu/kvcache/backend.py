"""The single KV-backend resolver: one decision object for the serving pool.

The serving cache has four orthogonal axes — dense slot pool vs paged
block tables (``kv_pages``), compute-dtype vs int8 payloads
(``kv_dtype``), XLA gathered read vs the Pallas fill-bounded kernels
(``kv_kernel``), and single-device vs mesh-sharded pools (``mesh``) —
and until PR 13 the four composed by EXCLUSION: ``kv_pages`` rejected
any mesh outright and ``kv_kernel`` hard-disabled whenever a mesh was
set, so the two flagship optimizations could never serve together and
sharded serving had zero paged/kernel rows anywhere (ROADMAP item 1,
VERDICT r5 weak #1).

``resolve_kv_backend`` replaces those blanket branches with a
CAPABILITY PROBE: it validates only what is genuinely unsupported
(raising a precise, regression-tested error per exclusion) and returns
a ``KVBackend`` describing the composed configuration — which pool
layout, which payload dtype, whether the Pallas read engages and, when
it does not, the machine-readable reason (surfaced on
``ServeMetrics`` so the ``kv_kernel="auto"`` threshold decision is
observable instead of silent).

Genuine exclusions (each raises):

- ``kv_pages`` + MoE: the paged suffix prefill routes experts densely
  (decode's rule) while the dense prefill uses the training dispatch —
  serving both would break the cache-on/off exactness contract.
- legacy per-record paged admission (``prefill_chunk=0``) + int8: the
  PR-4 baseline is compute-dtype only (unchanged).
- legacy per-record paged admission + mesh: the per-record suffix
  prefill is a ``[1, S]`` dispatch whose singleton batch cannot shard
  over ``data``; the chunked tick (``prefill_chunk`` None or >= 1) is
  the sharded spelling.
- ``kv_kernel=True`` that cannot be honored (tiling shapes, block
  size, or a mesh the slots/heads don't divide): require-or-raise, so
  a benchmark never misattributes the XLA read's numbers to the
  kernel.

Everything else composes. Under a mesh the pools shard exactly like
the dense slot pool — kv heads over ``tp``, per-slot state over
``data`` — with the paged BLOCK pools replicated over ``data`` (blocks
are shared storage addressed by every slot's table; the per-slot
tables themselves are replicated operands) and the Pallas reads
wrapped in ``shard_map`` (``ops.kvattn.*_sharded`` — the
``flash_attention_sharded`` precedent: batch/head-parallel attention
needs no collectives, so each (data, tp) shard runs the kernel over
its own slots and heads).
"""

from __future__ import annotations

import dataclasses

__all__ = ["KVBackend", "resolve_kv_backend"]

# Pool length at/above which kv_kernel="auto" engages the Pallas reads:
# the kernels' advantage grows with pool bytes while their fixed
# in-tick cost does not — measured win at 1024/2048, measured loss at
# 192 (serve.py's full matrix; PERF.md).
KV_KERNEL_AUTO_MIN_POOL = 1024


@dataclasses.dataclass(frozen=True)
class KVBackend:
    """The resolved serving-cache configuration — what actually serves.

    ``layout``: "dense" (per-slot pool) or "paged" (block pool + per-
    slot tables). ``int8``: quantized payloads + group-wise scales.
    ``kernel``: the Pallas fill-bounded read engages on decode ticks.
    ``kernel_disabled_reason``: why it does NOT engage (None when it
    does, or when int8 was never requested — there is no kernel
    without an int8 pool). ``data``/``tp``: mesh axis extents (1 =
    unsharded axis; both 1 = single device)."""

    layout: str
    int8: bool
    kernel: bool
    kernel_disabled_reason: str | None
    chunked: bool
    data: int
    tp: int

    @property
    def paged(self) -> bool:
        return self.layout == "paged"

    @property
    def sharded(self) -> bool:
        return self.data > 1 or self.tp > 1

    def describe(self) -> dict:
        """The ``ServeMetrics`` ``kv_backend`` info payload."""
        return {
            "layout": self.layout,
            "kv_dtype": "int8" if self.int8 else "compute",
            "kernel": self.kernel,
            "kernel_disabled_reason": self.kernel_disabled_reason,
            "chunked": self.chunked,
            "data": self.data,
            "tp": self.tp,
        }


def _kernel_probe_dense(cfg, max_len: int, on_tpu: bool) -> str | None:
    """None = honorable; else the reason the dynamic-length kernel
    cannot run on this dense pool."""
    from torchkafka_tpu.ops.kvattn import dynlen_block, kernel_applicable

    if not kernel_applicable(cfg.head_dim, max_len):
        return (
            f"tiling: head_dim={cfg.head_dim} % 128 or "
            f"pool_len={max_len} % 8"
        )
    if dynlen_block(max_len) < (256 if on_tpu else 8):
        return (
            f"tiling: pool_len={max_len} has no >= 256 DMA block "
            f"(dynlen_block={dynlen_block(max_len)})"
        )
    return None


def _kernel_probe_paged(cfg, block_size: int, on_tpu: bool) -> str | None:
    """None = honorable; else why the block-table kernel cannot run."""
    from torchkafka_tpu.ops.kvattn import paged_kernel_applicable

    # Tiling gates COMPILED Mosaic only; off-TPU the kernel runs in
    # Pallas interpret mode (the tests' differential path), which
    # accepts any shape.
    if on_tpu and not (
        paged_kernel_applicable(cfg.head_dim, block_size)
        and block_size >= 256
    ):
        return (
            f"tiling: head_dim={cfg.head_dim} % 128, block_size="
            f"{block_size} % 8, and block_size >= 256 required on TPU"
        )
    return None


def _mesh_kernel_reason(cfg, mesh, slots: int) -> str | None:
    """None = the shard_map wrapping works on this mesh; else why not.

    The sharded kernels run per (data, tp) shard over local slots and
    local kv heads, so both must split evenly (``check_serving_mesh``
    enforces the same divisibilities for the XLA path — this re-states
    them as a kernel capability so ``auto`` degrades with a reason
    instead of a deep shape error)."""
    data = mesh.shape.get("data", 1)
    tp = mesh.shape.get("tp", 1)
    if data > 1 and slots % data:
        return f"mesh: slots={slots} % data={data}"
    if tp > 1 and (cfg.n_kv_heads % tp or cfg.n_heads % tp):
        return (
            f"mesh: n_kv_heads={cfg.n_kv_heads}/n_heads={cfg.n_heads} "
            f"% tp={tp}"
        )
    return None


def resolve_kv_backend(
    cfg,
    *,
    mesh=None,
    kv_dtype: str | None = None,
    kv_kernel: bool | str = "auto",
    kv_pages=None,
    max_len: int,
    slots: int,
    backend: str | None = None,
) -> KVBackend:
    """Validate one KV-backend combination and decide kernel engagement.

    Raises ``ValueError`` for the genuine exclusions (module
    docstring); otherwise returns the composed ``KVBackend``.
    ``backend``: the jax platform string ("tpu"/"cpu"/...) — off-TPU
    the kernels run in interpret mode, so ``auto`` never engages them
    there while ``True`` still honors the request for the tests'
    differential path."""
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
    # Identity checks, not ``in (True, False, 'auto')``: bool-int
    # equality would accept 1/0 and then treat them inconsistently
    # downstream (``kv_kernel is True`` guards would not fire for 1).
    if not (kv_kernel is True or kv_kernel is False or kv_kernel == "auto"):
        raise ValueError(
            f"kv_kernel must be True, False or 'auto', got {kv_kernel!r}"
        )
    int8 = kv_dtype == "int8"
    if kv_kernel is True and not int8:
        raise ValueError("kv_kernel requires kv_dtype='int8'")
    paged = kv_pages is not None
    chunked = paged and kv_pages.prefill_chunk != 0
    if paged:
        if kv_pages.prefill_chunk == 0 and int8:
            raise ValueError(
                "legacy per-record paged admission (prefill_chunk=0) "
                "is the PR-4 compute-dtype baseline; the int8 paged "
                "pool requires the chunked tick (prefill_chunk None "
                "or >= 1)"
            )
        if kv_pages.prefill_chunk == 0 and mesh is not None:
            raise ValueError(
                "legacy per-record paged admission (prefill_chunk=0) "
                "cannot serve under a mesh: its per-record suffix "
                "prefill is a [1, S] dispatch whose singleton batch "
                "has no data shard — use the chunked tick "
                "(prefill_chunk None or >= 1) or mesh=None"
            )
        if cfg.is_moe:
            raise ValueError(
                "kv_pages does not serve MoE configs: the paged suffix "
                "prefill routes experts densely (decode's rule) while "
                "the dense prefill uses the training dispatch, which "
                "would break the cache-on/off exactness contract"
            )
    on_tpu = backend == "tpu"
    data = mesh.shape.get("data", 1) if mesh is not None else 1
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1

    kernel = False
    reason: str | None = None
    if int8 and kv_kernel:
        if paged:
            reason = _kernel_probe_paged(cfg, kv_pages.block_size, on_tpu)
        else:
            reason = _kernel_probe_dense(cfg, max_len, on_tpu)
        if reason is None and mesh is not None:
            reason = _mesh_kernel_reason(cfg, mesh, slots)
        if kv_kernel is True:
            if reason is not None:
                raise ValueError(
                    f"kv_kernel=True cannot be honored here ({reason}); "
                    "the explicit request never falls back silently — a "
                    "benchmark must not misattribute the XLA read's "
                    "numbers to the kernel"
                )
            kernel = True
        else:  # "auto": engage only in the measured-win regime
            if reason is None:
                if not on_tpu:
                    reason = f"auto: backend={backend!r} is not tpu"
                elif max_len < KV_KERNEL_AUTO_MIN_POOL:
                    reason = (
                        f"auto: pool_len={max_len} < "
                        f"{KV_KERNEL_AUTO_MIN_POOL}"
                    )
                else:
                    kernel = True
    elif kv_kernel and not int8:
        # auto without int8: there is no kernel for compute-dtype pools.
        reason = "auto: kv_dtype is not 'int8'"
    return KVBackend(
        layout="paged" if paged else "dense",
        int8=int8,
        kernel=kernel,
        kernel_disabled_reason=None if kernel else reason,
        chunked=chunked,
        data=data,
        tp=tp,
    )

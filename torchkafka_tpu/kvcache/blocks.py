"""Paged KV-cache block pool: allocator, refcounts, block tables.

The serving path's answer to "HBM scales as pool × max_context" (the 8B
long-context OOM in VERDICT.md): instead of a dense per-slot cache
``[B, max_len, K, Dh]``, the pool is a fixed set of fixed-size BLOCKS
``[num_blocks, block_size, K, Dh]`` and each decode slot maps logical
positions to physical blocks through a block table ``[B, max_blocks]``
— the TPU-idiomatic, static-shape version of vLLM's PagedAttention.
Every shape the device sees is static: the pool, the tables, the
gathered per-slot view; only the HOST-side mapping (this module) is
dynamic.

Blocks are REFCOUNTED so several slots can map the same physical
prefix blocks (RadixCache hands them out, kvcache/radix.py): a cached
prefix block carries one reference from the radix tree plus one per
slot currently mapping it. A block returns to the free list exactly
when its count reaches zero — never while anything can still read it.

Physical block 0 is the SINK: it backs the table rows of idle slots,
so the decode tick's unconditional scatter write (an inactive slot
still writes its frozen position — masking the write would cost a
pool-sized select per layer, serve.py's lesson) lands in a block no
live table ever references, instead of corrupting a block that was
freed and re-allocated to another slot. The allocator never hands out
block 0.

Host-side and deterministic: LIFO free list, explicit refcounts, no
clocks — the same admission sequence always produces the same physical
layout, which is what makes the cache-on/cache-off differential (and
chaos replay) exactly comparable.
"""

from __future__ import annotations

import dataclasses

SINK_BLOCK = 0


@dataclasses.dataclass(frozen=True)
class PagedKVConfig:
    """Configuration for a paged slot pool (``StreamingGenerator``'s
    ``kv_pages=``).

    ``block_size``: tokens per physical block — sharing granularity
    (only whole blocks are shared; a finer size shares more of a
    prefix but makes the table longer). ``num_blocks``: physical
    blocks in the pool INCLUDING the sink; usable capacity is
    ``num_blocks - 1``. A pool smaller than one slot's worst case
    (``ceil(max_len / block_size)`` blocks) cannot serve at all —
    the server then falls back to the dense cache-off path
    (gracefully, with a warning) rather than deadlocking admission.

    ``prefill_chunk``: admission mode. ``None`` (default) = CHUNKED
    prefill fused into the decode tick — admission enqueues each
    prompt's uncached suffix host-side and every tick processes a
    bounded, statically-shaped chunk of those tokens ALONGSIDE all
    decode slots in ONE jitted program (Sarathi-style: prefill rides
    the weight stream decode already pays for), with the chunk width
    auto-sized to ``slots * prompt_len`` (every admission a single
    serving quantum can offer completes in one tick, preserving the
    per-record completion timing of the per-record path shifted by
    exactly one tick). An explicit int >= 1 fixes the chunk width —
    smaller widths bound how much prefill work any one tick carries
    (the decode-latency lever under prompt storms; a prompt storm
    then drains FIFO at ``prefill_chunk`` tokens per tick while
    in-flight decode keeps emitting one token per slot per tick).
    ``0`` = the LEGACY per-record admission (one suffix-prefill
    dispatch per record, a jit specialisation per suffix length) —
    kept as the measured PR-4 baseline and differential reference.
    """

    block_size: int
    num_blocks: int
    prefill_chunk: int | None = None

    def __post_init__(self) -> None:
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the sink), "
                f"got {self.num_blocks}"
            )
        if self.prefill_chunk is not None and self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be None (auto), 0 (legacy per-record "
                f"admission) or >= 1, got {self.prefill_chunk}"
            )

    def blocks_per_slot(self, max_len: int) -> int:
        """Blocks one slot needs to hold ``max_len`` positions."""
        return -(-max_len // self.block_size)


class BlockAllocator:
    """Free-list block allocator with refcounts.

    ``alloc(n)`` hands out ``n`` blocks at refcount 1 (the caller's
    slot reference) or ``None`` if the free list is short — the caller
    decides whether to evict (RadixCache) or defer the admission.
    ``incref``/``decref`` move cache/slot references; a decref to zero
    frees the block. Counts can never go negative: ``decref`` on a
    free block raises, which is how the property tests pin the
    invariant.
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the sink), "
                f"got {num_blocks}"
            )
        self.num_blocks = num_blocks
        # LIFO free list over [1, num_blocks): low ids first out, so
        # identical admission sequences produce identical layouts.
        self._free = list(range(num_blocks - 1, 0, -1))
        self._ref = [0] * num_blocks

    @property
    def usable(self) -> int:
        """Allocatable blocks (the pool minus the sink)."""
        return self.num_blocks - 1

    def available(self) -> int:
        return len(self._free)

    def allocated(self) -> int:
        return self.usable - len(self._free)

    def occupancy(self) -> float:
        return self.allocated() / self.usable if self.usable else 0.0

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def alloc(self, n: int) -> list[int] | None:
        """``n`` fresh blocks at refcount 1, or None (nothing allocated)
        if the free list holds fewer than ``n`` — allocation is
        all-or-nothing so a half-admitted slot never exists."""
        if n < 0:
            raise ValueError(f"cannot alloc {n} blocks")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == SINK_BLOCK:
                raise ValueError("the sink block is never referenced")
            if self._ref[b] <= 0:
                raise ValueError(f"incref on free block {b}")
            self._ref[b] += 1

    def decref(self, blocks: list[int]) -> list[int]:
        """Drop one reference per block; blocks reaching zero return to
        the free list. Returns the freed blocks (for metrics/tests)."""
        freed = []
        for b in blocks:
            if b == SINK_BLOCK:
                raise ValueError("the sink block is never referenced")
            if self._ref[b] <= 0:
                raise ValueError(f"decref on free block {b} (refcount bug)")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)
                freed.append(b)
        return freed

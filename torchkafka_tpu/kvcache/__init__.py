"""Paged KV-cache pool with radix-tree prefix reuse for the serving path.

Host-side machinery (static device shapes live in ops/kvattn.py and the
servers): ``BlockAllocator`` — refcounted free-list blocks over a
``[num_blocks, block_size, K, Dh]`` pool, block 0 reserved as the sink
for idle-slot writes; ``RadixCache`` — prompt-prefix tree mapping whole
block runs, LRU-evicting unreferenced leaves (eviction is advisory: a
miss just re-prefills, token-exactness never depends on the cache);
``PagedKVConfig`` — the ``StreamingGenerator(kv_pages=...)`` knob;
``resolve_kv_backend`` — the single capability probe deciding how the
four cache axes (dense/paged × compute/int8 × gather/kernel ×
single-device/mesh) compose for one server (kvcache/backend.py).
"""

from torchkafka_tpu.kvcache.backend import (
    KV_KERNEL_AUTO_MIN_POOL,
    KVBackend,
    resolve_kv_backend,
)
from torchkafka_tpu.kvcache.blocks import (
    SINK_BLOCK,
    BlockAllocator,
    PagedKVConfig,
)
from torchkafka_tpu.kvcache.radix import RadixCache
from torchkafka_tpu.kvcache.tier import HostTier, TierConfig

__all__ = [
    "BlockAllocator",
    "HostTier",
    "KVBackend",
    "KV_KERNEL_AUTO_MIN_POOL",
    "PagedKVConfig",
    "RadixCache",
    "SINK_BLOCK",
    "TierConfig",
    "resolve_kv_backend",
]

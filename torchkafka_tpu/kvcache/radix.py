"""Host-side radix tree over prompt token prefixes → physical block runs.

Cross-request prefix reuse for the paged serving pool (SGLang's
RadixAttention shape, block-granular): prompts streamed from a Kafka
topic that share a tenant/system-prompt prefix map the SAME physical
blocks for the shared part and prefill only the uncached suffix.

Granularity is one BLOCK of ``block_size`` tokens per tree edge — only
whole blocks are shared, so a shared block is always entirely inside
the matched prefix and is never written again after it is cached
(decode writes land strictly beyond the prompt; the straddling partial
block stays private). That is what makes copy-on-write unnecessary.

The match is capped at ``prompt_len - 1`` tokens: admission always
prefills at least the prompt's final token, because sampling token 0
needs the last position's logits (the standard full-hit rule).

EVICTION IS ADVISORY: the tree only ever holds blocks alive (one cache
reference each); evicting an unreferenced leaf frees its block, and the
only consequence is that a future prompt re-prefills — token-exactness
NEVER depends on what the cache holds. LRU over leaves, cascading
upward while parents become unreferenced leaves themselves.

Determinism: no wall clock — the LRU ticks on a monotone counter
advanced per operation, so the same admission sequence evicts the same
blocks (the property the chaos-replay differential rests on).
"""

from __future__ import annotations

from torchkafka_tpu.kvcache.blocks import BlockAllocator


class _Node:
    __slots__ = ("chunk", "block", "children", "parent", "stamp")

    def __init__(self, chunk: tuple, block: int, parent: "_Node | None"):
        self.chunk = chunk          # the block_size tokens this edge spells
        self.block = block          # physical block holding their k/v
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.stamp = 0              # LRU tick of the last match/insert touch


class RadixCache:
    """Prefix cache over an allocator's blocks.

    The tree owns ONE reference on every block it maps (taken at
    ``insert``, dropped at eviction); ``match`` adds a slot reference
    per returned block, which the server drops via
    ``allocator.decref`` when the slot retires. ``evict`` frees LRU
    leaves whose blocks carry no reference beyond the tree's own.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int, *,
                 tier=None, read_block=None, write_block=None) -> None:
        """``tier``/``read_block``/``write_block``: the host-RAM tier
        (kvcache/tier.py ``HostTier``) plus the pool I/O the server
        supplies — ``read_block(block_id) -> payload`` fetches a block's
        KV bytes to host (demotion source), ``write_block(block_id,
        payload)`` scatters them back (promotion sink). With a tier,
        ``evict`` DEMOTES unreferenced leaves instead of just freeing
        them, and ``match`` PROMOTES tier entries that extend a prefix
        walk into freshly allocated blocks — so a "miss" against the
        in-HBM tree can still be a hit against host memory. Both sides
        stay advisory: a tier miss (or a promotion that finds no free
        block) simply re-prefills, exactly like eviction always did."""
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if tier is not None and (read_block is None or write_block is None):
            raise ValueError(
                "a tier needs read_block and write_block (the pool I/O "
                "that moves payloads between HBM and the tier)"
            )
        self._alloc = allocator
        self._bs = block_size
        self._tier = tier
        self._read_block = read_block
        self._write_block = write_block
        self._root = _Node((), -1, None)
        self._clock = 0
        self.cached_blocks = 0
        # Tier traffic counters (the server mirrors them onto
        # ServeMetrics after each admission/eviction sweep).
        self.demotions = 0
        self.promotions = 0
        self.tier_hits = 0

    # ------------------------------------------------------------- helpers

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunks(self, tokens, limit_blocks: int):
        bs = self._bs
        n = min(limit_blocks, len(tokens) // bs)
        for j in range(n):
            yield tuple(int(t) for t in tokens[j * bs:(j + 1) * bs])

    @staticmethod
    def matchable_blocks(prompt_len: int, block_size: int) -> int:
        """Whole blocks of a prompt that can ever be shared: the final
        token is always prefilled (its logits sample token 0), so the
        shareable prefix is at most ``prompt_len - 1`` tokens."""
        return max(0, (prompt_len - 1) // block_size)

    # ----------------------------------------------------------------- api

    @staticmethod
    def _prefix_key(chunks: list[tuple]) -> bytes:
        """Tier key for the prefix spelled by ``chunks`` (root→node token
        path as int32 bytes) — physical ids are meaningless across
        demote/promote cycles, token prefixes are not."""
        import numpy as np

        return np.asarray(
            [t for c in chunks for t in c], np.int32
        ).tobytes()

    def _node_key(self, node: _Node) -> bytes:
        chunks = []
        while node is not self._root:
            chunks.append(node.chunk)
            node = node.parent
        return self._prefix_key(chunks[::-1])

    def match(self, tokens) -> list[int]:
        """Longest cached whole-block prefix of ``tokens`` (capped at
        ``matchable_blocks``) → physical block ids in logical order.
        Takes one SLOT reference per returned block (caller decrefs when
        the slot retires) and refreshes the path's LRU stamps.

        With a tier attached, a walk that falls off the in-HBM tree
        keeps going against the tier: each matching tier entry is
        PROMOTED — a fresh block allocated (never evicting: promotion
        under pool pressure just stops, the prefix re-prefills), the
        payload scattered back via ``write_block``, and a node inserted
        holding the tree's reference — before the walk continues. The
        promoted bytes are exactly the demoted bytes, which are exactly
        what a re-prefill would compute, so serving stays token-exact
        whether this returns a block or not."""
        stamp = self._tick()
        cap = self.matchable_blocks(len(tokens), self._bs)
        node = self._root
        out: list[int] = []
        path: list[tuple] = []
        for chunk in self._chunks(tokens, cap):
            child = node.children.get(chunk)
            if child is None and self._tier is not None:
                key = self._prefix_key(path + [chunk])
                if self._tier.contains(key):
                    blk = self._alloc.alloc(1)
                    if blk is None:
                        break  # pool pressure: stop promoting, re-prefill
                    payload = self._tier.take(key)
                    self._write_block(blk[0], payload)
                    child = _Node(chunk, blk[0], node)
                    node.children[chunk] = child
                    self.cached_blocks += 1
                    self.promotions += 1
                    self.tier_hits += 1
            if child is None:
                break
            child.stamp = stamp
            out.append(child.block)
            node = child
            path.append(chunk)
        if out:
            self._alloc.incref(out)
        return out

    def insert(self, tokens, blocks: list[int]) -> int:
        """Register ``blocks`` (the slot's table entries for the first
        ``len(blocks)`` whole blocks of ``tokens``) as cached prefix
        blocks. Existing nodes are left in place (the slot got those
        blocks FROM the tree, so the ids must agree); new nodes adopt
        the slot's private blocks with one cache reference each.
        Returns the number of blocks newly cached."""
        stamp = self._tick()
        node = self._root
        added = 0
        for j, chunk in enumerate(self._chunks(tokens, len(blocks))):
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, blocks[j], node)
                node.children[chunk] = child
                self._alloc.incref([blocks[j]])
                self.cached_blocks += 1
                added += 1
            elif child.block != blocks[j]:
                raise AssertionError(
                    f"radix divergence at depth {j}: cached block "
                    f"{child.block} vs slot block {blocks[j]} — a slot's "
                    "table must reuse the tree's block wherever a node "
                    "exists (match-before-insert contract)"
                )
            child.stamp = stamp
            node = child
        return added

    # ------------------------------------------------------------ eviction

    def _evictable_leaves(self) -> list[_Node]:
        out = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                if child.children:
                    stack.append(child)
                elif self._alloc.refcount(child.block) == 1:
                    out.append(child)  # only the tree's own reference
        return out

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` via LRU leaf eviction (cascading: a
        parent that becomes an unreferenced leaf is immediately
        eligible). Returns blocks actually freed — fewer than asked is
        normal when the rest of the tree is pinned by live slots."""
        freed = 0
        while freed < n_blocks:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: nd.stamp)
            while victim is not None and freed < n_blocks:
                parent = victim.parent
                assert parent is not None
                if self._tier is not None:
                    # DEMOTE before freeing: the block's bytes are valid
                    # until a later alloc rewrites them, so the host copy
                    # taken here is exact. The tier's own LRU/spill
                    # policy decides how long the prefix survives.
                    self._tier.put(
                        self._node_key(victim),
                        self._read_block(victim.block),
                    )
                    self.demotions += 1
                del parent.children[victim.chunk]
                self._alloc.decref([victim.block])
                self.cached_blocks -= 1
                freed += 1
                # Cascade upward while the parent is itself an
                # unreferenced leaf (saves a full re-scan per block).
                victim = (
                    parent
                    if (
                        parent is not self._root
                        and not parent.children
                        and self._alloc.refcount(parent.block) == 1
                    )
                    else None
                )
        return freed
